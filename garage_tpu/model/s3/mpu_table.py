"""Multipart upload table.

Equivalent of reference src/model/s3/mpu_table.rs (SURVEY.md §2.6):
P = upload uuid; parts are a grow-only map (part_number, timestamp) →
{version uuid, etag, size}, where each part's data lives in its own
Version row.  Deleting the upload clears parts and the `updated()` hook
tombstones every part version (mpu_table.rs parts → version deletions).
Counted per-bucket: uploads / parts / bytes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ...table.schema import Entry, TableSchema
from ...utils.crdt import CrdtBool
from ...utils.data import Uuid

UPLOADS = "uploads"
PARTS = "parts"
BYTES_MPU = "bytes"


class MpuPart:
    """{version, etag, size} — dict carrier (ref mpu_table.rs MpuPart)."""

    @staticmethod
    def new(version: bytes, etag: Optional[str], size: Optional[int]) -> Dict:
        return {"version": bytes(version), "etag": etag, "size": size}


def _merge_part(a: Dict, b: Dict) -> Dict:
    # parts are atomic {version, etag, size}; prefer a completed part
    # (etag set), then a deterministic max tie-break so concurrent
    # same-key registrations converge on every replica (commutative,
    # like the reference's AutoCrdt max-merge on MpuPart)
    a_done = a.get("etag") is not None
    b_done = b.get("etag") is not None
    if a_done != b_done:
        return dict(a) if a_done else dict(b)
    ka = (bytes(a["version"]), a.get("etag") or "", a.get("size") or 0)
    kb = (bytes(b["version"]), b.get("etag") or "", b.get("size") or 0)
    return dict(a) if ka >= kb else dict(b)


class MultipartUpload(Entry):
    VERSION_MARKER = b"GT01mpu"

    def __init__(
        self,
        upload_id: Uuid,
        timestamp: int,
        bucket_id: bytes,
        key: str,
        deleted: bool = False,
        parts: Optional[Dict[Tuple[int, int], Dict]] = None,
    ):
        self.upload_id = upload_id
        self.timestamp = timestamp
        self.bucket_id = bytes(bucket_id)
        self.key = key
        self.deleted = CrdtBool(deleted)
        # (part_number, timestamp) → MpuPart
        self.parts: Dict[Tuple[int, int], Dict] = parts or {}
        if deleted:
            self.parts = {}

    @property
    def partition_key(self) -> Uuid:
        return self.upload_id

    @property
    def sort_key(self) -> str:
        return ""

    def is_tombstone(self) -> bool:
        return self.deleted.value

    def sorted_parts(self) -> List[Tuple[Tuple[int, int], Dict]]:
        return sorted(self.parts.items())

    def part_for(self, part_number: int) -> Optional[Dict]:
        """Latest registered part for this part number (re-uploads of the
        same part number supersede by timestamp)."""
        best = None
        for (pn, ts), p in self.parts.items():
            if pn == part_number and (best is None or ts > best[0]):
                best = (ts, p)
        return best[1] if best else None

    def merge(self, other: "MultipartUpload") -> None:
        self.deleted.merge(other.deleted)
        if self.deleted.value:
            self.parts = {}
            return
        for k, v in other.parts.items():
            mine = self.parts.get(k)
            self.parts[k] = v if mine is None else _merge_part(mine, v)

    def counts(self) -> List[Tuple[str, int]]:
        if self.deleted.value:
            return [(UPLOADS, 0), (PARTS, 0), (BYTES_MPU, 0)]
        return [
            (UPLOADS, 1),
            (PARTS, len(self.parts)),
            (BYTES_MPU, sum(p["size"] or 0 for p in self.parts.values())),
        ]

    def fields(self) -> Any:
        return [
            bytes(self.upload_id),
            self.timestamp,
            self.bucket_id,
            self.key,
            self.deleted.value,
            [[list(k), [v["version"], v["etag"], v["size"]]] for k, v in self.sorted_parts()],
        ]

    @classmethod
    def from_fields(cls, b: Any) -> "MultipartUpload":
        return cls(
            Uuid(bytes(b[0])),
            int(b[1]),
            bytes(b[2]),
            b[3],
            deleted=bool(b[4]),
            parts={
                (int(k[0]), int(k[1])): {"version": bytes(v[0]), "etag": v[1], "size": v[2]}
                for k, v in b[5]
            },
        )


class MpuTableSchema(TableSchema):
    TABLE_NAME = "multipart_upload"
    ENTRY = MultipartUpload

    def __init__(self, version_table=None, counter=None):
        self.version_table = version_table
        self.counter = counter

    def updated(self, tx, old: Optional[MultipartUpload], new: Optional[MultipartUpload]) -> None:
        from .version_table import Version

        if self.counter is not None:
            self.counter.count(
                tx,
                bytes((old or new).bucket_id),
                "",
                old.counts() if old is not None else [],
                new.counts() if new is not None else [],
            )
        if (
            self.version_table is not None
            and old is not None
            and new is not None
            and new.deleted.value
            and not old.deleted.value
        ):
            # tombstone every part version (ref mpu_table.rs updated)
            for (_k, part) in old.sorted_parts():
                vdel = Version(
                    Uuid(part["version"]),
                    old.bucket_id,
                    old.key,
                    deleted=True,
                    mpu_upload_id=bytes(old.upload_id),
                )
                self.version_table.data.queue_insert(tx, vdel)

    def matches_filter(self, entry: MultipartUpload, filter: Any) -> bool:
        from ...table.schema import DeletedFilter

        if filter is None:
            return not entry.deleted.value
        return DeletedFilter.matches(filter, entry.deleted.value)
