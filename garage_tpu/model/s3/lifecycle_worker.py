"""Lifecycle worker — applies bucket lifecycle rules (expiration and
abort-incomplete-multipart-upload) in a daily resumable pass.

Equivalent of reference src/model/s3/lifecycle_worker.rs:36-103:
  - one pass per UTC day over the whole local object table, in tree-key
    order (hash(bucket) ‖ key), batches of 100 objects per work() step;
  - per object: load its bucket (cached while the walk stays in the same
    bucket — the walk is bucket-hash-ordered so each bucket is one
    contiguous run), apply each enabled rule whose prefix/size filters
    match:
      * Expiration Days/Date → insert a DeleteMarker tombstone version,
      * AbortIncompleteMultipartUpload DaysAfterInitiation → mark old
        Uploading versions Aborted (the object-table hook cascades the
        cleanup to version/block_ref rows);
  - buckets with no enabled rules are skipped wholesale by jumping the
    position cursor past the bucket's 32-byte hash prefix;
  - the last completed date persists (Persister) so restarts within the
    same day do not rerun, and mid-pass restarts rerun idempotently from
    the start of the day (expiring twice is a no-op: the tombstone is
    already the newest version).
"""

from __future__ import annotations

import asyncio
import datetime
import logging
from typing import Optional

from ...utils.background import Worker, WorkerState
from ...utils.crdt import now_msec
from ...utils.data import gen_uuid
from ...utils.migrate import Migrated
from ...utils.persister import Persister
from .object_table import Object, ObjectVersion, ObjectVersionData

logger = logging.getLogger("garage_tpu.model.lifecycle")

BATCH = 100  # objects per work() step (ref lifecycle_worker.rs:163)


class LifecycleWorkerPersisted(Migrated):
    """ref lifecycle_worker.rs v090::LifecycleWorkerPersisted."""

    VERSION_MARKER = b"GT01lwp"

    def __init__(self, last_completed: Optional[str] = None):
        self.last_completed = last_completed

    def fields(self):
        return [self.last_completed]

    @classmethod
    def from_fields(cls, b):
        return cls(*b)


def today() -> datetime.date:
    """UTC date; module-level so tests can monkeypatch time travel."""
    return datetime.datetime.now(datetime.timezone.utc).date()


def next_date(ts_ms: int) -> datetime.date:
    """Date after the timestamp's date — a version 'counts' from the first
    full day after it was written (ref lifecycle_worker.rs next_date)."""
    d = datetime.datetime.fromtimestamp(
        ts_ms / 1000.0, tz=datetime.timezone.utc
    ).date()
    return d + datetime.timedelta(days=1)


def parse_lifecycle_date(s: str) -> Optional[datetime.date]:
    try:
        return datetime.datetime.fromisoformat(s.replace("Z", "+00:00")).date()
    except ValueError:
        return None


def _midnight_after(d: datetime.date) -> float:
    nxt = d + datetime.timedelta(days=1)
    dt = datetime.datetime.combine(
        nxt, datetime.time(0, 0), tzinfo=datetime.timezone.utc
    )
    return dt.timestamp()


class LifecycleWorker(Worker):
    def __init__(self, garage, persister: Persister):
        self.garage = garage
        self.persister = persister
        st = persister.load()
        last = (
            datetime.date.fromisoformat(st.last_completed)
            if st is not None and st.last_completed
            else None
        )
        t = today()
        if last is not None and last >= t:
            self.date: Optional[datetime.date] = None  # completed for today
            self.last_completed = last
        else:
            self._start(t)
            self.last_completed = last

    def _start(self, date: datetime.date) -> None:
        logger.info("starting lifecycle pass for %s", date)
        self.date = date
        self.pos = b""
        self.counter = 0
        self.objects_expired = 0
        self.mpu_aborted = 0
        self._bucket_cache: Optional[tuple] = None  # (bucket_id_bytes, bucket)

    def name(self) -> str:
        return "Object lifecycle worker"

    async def work(self) -> WorkerState:
        if self.date is None:
            return WorkerState.IDLE
        data = self.garage.object_table.data
        for _ in range(BATCH):
            nxt = data.store.get_gt(self.pos)
            if nxt is None:
                logger.info(
                    "lifecycle pass for %s done: %d expired, %d mpu aborted",
                    self.date, self.objects_expired, self.mpu_aborted,
                )
                self.last_completed = self.date
                self.persister.save(
                    LifecycleWorkerPersisted(self.date.isoformat())
                )
                self.date = None
                return WorkerState.IDLE
            key, val = nxt
            try:
                obj = data.decode_entry(val)
            except Exception:
                logger.exception("lifecycle: undecodable object row")
                self.pos = key
                continue
            skip_bucket = await self.process_object(obj)
            self.counter += 1
            self.status().progress = f"{self.counter} objects"
            if skip_bucket:
                # jump past every remaining key of this bucket: tree keys
                # are hash(bucket_id)(32B) ‖ object key
                self.pos = max(key, key[:32] + b"\xff" * 8)
            else:
                self.pos = key
        return WorkerState.BUSY

    async def process_object(self, obj: Object) -> bool:
        """Apply the bucket's rules to one object; True = the whole bucket
        can be skipped (no enabled rules / bucket gone)."""
        if not any(v.is_data() or v.is_uploading() for v in obj.versions()):
            return False
        bid = bytes(obj.bucket_id)
        if self._bucket_cache is not None and self._bucket_cache[0] == bid:
            bucket = self._bucket_cache[1]
        else:
            bucket = await self.garage.bucket_table.get(obj.bucket_id, "")
            if bucket is None or bucket.is_deleted():
                logger.warning("lifecycle: object in missing bucket %s", bid.hex()[:16])
                return True
            self._bucket_cache = (bid, bucket)
        rules = bucket.params().lifecycle_config.value or []
        if not any(r.get("enabled") for r in rules):
            return True

        now_date = self.date
        for rule in rules:
            if not rule.get("enabled"):
                continue
            prefix = rule.get("prefix") or ""
            if prefix and not obj.key.startswith(prefix):
                continue

            days = rule.get("expiration_days")
            at_date = rule.get("expiration_date")
            if days is not None or at_date:
                cur = obj.last_data_version()
                if cur is not None and self._size_match(cur, rule):
                    if days is not None:
                        expired = (
                            now_date - next_date(cur.timestamp)
                        ).days >= days
                    else:
                        exp = parse_lifecycle_date(at_date)
                        if exp is None:
                            logger.warning(
                                "invalid lifecycle date %r in bucket %s",
                                at_date, bid.hex()[:16],
                            )
                            expired = False
                        else:
                            expired = now_date >= exp
                    if expired:
                        marker = ObjectVersion(
                            gen_uuid(),
                            max(now_msec(), cur.timestamp + 1),
                            ["complete", ObjectVersionData.delete_marker()],
                        )
                        logger.info("lifecycle: expiring %s", obj.key)
                        await self.garage.object_table.insert(
                            Object(obj.bucket_id, obj.key, [marker])
                        )
                        self.objects_expired += 1

            abort_days = rule.get("abort_incomplete_days")
            if abort_days is not None:
                from .object_table import abort_uploads

                n = await abort_uploads(
                    self.garage.object_table, obj,
                    lambda v: (now_date - next_date(v.timestamp)).days
                    >= abort_days,
                )
                if n:
                    logger.info(
                        "lifecycle: aborting %d stale upload(s) of %s",
                        n, obj.key,
                    )
                    self.mpu_aborted += n
        return False

    @staticmethod
    def _size_match(version: ObjectVersion, rule: dict) -> bool:
        size = version.size()
        gt, lt = rule.get("size_gt"), rule.get("size_lt")
        if gt is not None and not size > gt:
            return False
        if lt is not None and not size < lt:
            return False
        return True

    async def wait_for_work(self) -> None:
        if self.date is not None:
            return
        base = self.last_completed or today()
        delay = max(1.0, _midnight_after(base) - datetime.datetime.now(
            datetime.timezone.utc
        ).timestamp())
        # wake at most every 10 s so time-travel tests and shutdown stay
        # responsive (the reference sleeps the full interval; our Worker
        # protocol re-polls work() which is a cheap IDLE)
        await asyncio.sleep(min(delay, 10.0))
        t = today()
        if self.last_completed is None or self.last_completed < t:
            self._start(t)
