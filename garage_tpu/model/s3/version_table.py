"""Version table — block lists of object versions.

Equivalent of reference src/model/s3/version_table.rs (SURVEY.md §2.6):
P = version uuid; the row maps (part_number, offset) → (block hash, size)
plus per-part etags, with a deletion flag that clears the maps on merge
(version_table.rs:14-160).  The `updated()` hook marks every referenced
block's BlockRef deleted when the version is deleted (version_table.rs:259+)
— the step that eventually drops block refcounts.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ...table.schema import Entry, TableSchema
from ...utils.crdt import CrdtBool
from ...utils.data import Hash, Uuid


class VersionBlockKey:
    """(part_number, offset) — ordering = block order in the object."""

    @staticmethod
    def key(part_number: int, offset: int) -> Tuple[int, int]:
        return (part_number, offset)


class VersionBlock:
    """{hash, size} (ref version_table.rs:60-68). Tuple carrier (hash, size)."""

    @staticmethod
    def new(hash32: bytes, size: int) -> Tuple[bytes, int]:
        return (bytes(hash32), size)


class Version(Entry):
    """ref version_table.rs:14-160."""

    VERSION_MARKER = b"GT01version"

    def __init__(
        self,
        uuid: Uuid,
        bucket_id: bytes,
        key: str,
        deleted: bool = False,
        blocks: Optional[Dict[Tuple[int, int], Tuple[bytes, int]]] = None,
        parts_etags: Optional[Dict[int, str]] = None,
        mpu_upload_id: Optional[bytes] = None,
    ):
        self.uuid = uuid
        # backlink (ref VersionBacklink): object (bucket,key) or the MPU id
        self.bucket_id = bytes(bucket_id)
        self.key = key
        self.mpu_upload_id = mpu_upload_id
        self.deleted = CrdtBool(deleted)
        # (part_number, offset) → (hash, size); grow-only until deleted
        self.blocks: Dict[Tuple[int, int], Tuple[bytes, int]] = blocks or {}
        self.parts_etags: Dict[int, str] = parts_etags or {}
        if deleted:
            self.blocks, self.parts_etags = {}, {}

    @classmethod
    def new(cls, uuid: Uuid, bucket_id: bytes, key: str, deleted: bool = False) -> "Version":
        return cls(uuid, bucket_id, key, deleted=deleted)

    @property
    def partition_key(self) -> Uuid:
        return self.uuid

    @property
    def sort_key(self) -> str:
        return ""

    def is_tombstone(self) -> bool:
        return self.deleted.value

    def sorted_blocks(self) -> List[Tuple[Tuple[int, int], Tuple[bytes, int]]]:
        return sorted(self.blocks.items())

    def total_size(self) -> int:
        return sum(sz for (_h, sz) in self.blocks.values())

    def add_block(self, part_number: int, offset: int, hash32: bytes, size: int) -> None:
        if not self.deleted.value:
            self.blocks[(part_number, offset)] = (bytes(hash32), size)

    def merge(self, other: "Version") -> None:
        self.deleted.merge(other.deleted)
        if self.deleted.value:
            self.blocks, self.parts_etags = {}, {}
            return
        for k, v in other.blocks.items():
            mine = self.blocks.get(k)
            # values are deterministic for a given key; max-merge breaks ties
            self.blocks[k] = v if mine is None or v > mine else mine
        for p, e in other.parts_etags.items():
            mine_e = self.parts_etags.get(p)
            self.parts_etags[p] = e if mine_e is None or e > mine_e else mine_e

    def fields(self) -> Any:
        return [
            bytes(self.uuid),
            self.bucket_id,
            self.key,
            self.deleted.value,
            [[list(k), [v[0], v[1]]] for k, v in self.sorted_blocks()],
            sorted(self.parts_etags.items()),
            self.mpu_upload_id,
        ]

    @classmethod
    def from_fields(cls, b: Any) -> "Version":
        return cls(
            Uuid(bytes(b[0])),
            bytes(b[1]),
            b[2],
            deleted=bool(b[3]),
            blocks={(int(k[0]), int(k[1])): (bytes(v[0]), int(v[1])) for k, v in b[4]},
            parts_etags={int(p): e for p, e in b[5]},
            mpu_upload_id=bytes(b[6]) if b[6] is not None else None,
        )


class VersionTableSchema(TableSchema):
    TABLE_NAME = "version"
    ENTRY = Version

    def __init__(self, block_ref_table=None):
        self.block_ref_table = block_ref_table

    def updated(self, tx, old: Optional[Version], new: Optional[Version]) -> None:
        """ref version_table.rs updated(): deleting a version deletes all
        its block refs; blocks added to a live version insert live refs."""
        from .block_ref_table import BlockRef

        if self.block_ref_table is None:
            return
        if old is not None and new is not None and new.deleted.value and not old.deleted.value:
            for (_k, (h, _sz)) in old.sorted_blocks():
                self.block_ref_table.data.queue_insert(
                    tx, BlockRef(Hash(h), old.uuid, deleted=True)
                )
        elif new is not None and not new.deleted.value:
            old_blocks = set(h for (h, _s) in (old.blocks.values() if old else []))
            for (h, _sz) in new.blocks.values():
                if h not in old_blocks:
                    self.block_ref_table.data.queue_insert(
                        tx, BlockRef(Hash(h), new.uuid, deleted=False)
                    )

    def matches_filter(self, entry: Version, filter: Any) -> bool:
        from ...table.schema import DeletedFilter

        if filter is None:
            return not entry.deleted.value
        return DeletedFilter.matches(filter, entry.deleted.value)
