"""K2V causality tokens — vector clocks over writer nodes.

Equivalent of reference src/model/k2v/causality.rs:21-127: a CausalContext
maps writer node (first 8 bytes of its id, as u64) → the highest timestamp
of that node's writes the reader has seen.  Serialized as a base64url
token handed to clients; an insert carrying a token supersedes exactly the
values the token covers, everything else becomes a concurrent sibling.
"""

from __future__ import annotations

import base64
import struct
from typing import Dict, Optional


def node_id64(node_id: bytes) -> int:
    """Writer key = first 8 bytes of the 32-byte node id (ref
    causality.rs make_node_id)."""
    return struct.unpack(">Q", bytes(node_id)[:8])[0]


class CausalContext:
    __slots__ = ("vector_clock",)

    def __init__(self, vector_clock: Optional[Dict[int, int]] = None):
        self.vector_clock: Dict[int, int] = vector_clock or {}

    def serialize(self) -> str:
        """ref causality.rs:35-54: sorted (node u64, ts u64) pairs,
        big-endian, base64url without padding."""
        buf = b"".join(
            struct.pack(">QQ", n, t)
            for n, t in sorted(self.vector_clock.items())
        )
        return base64.urlsafe_b64encode(buf).decode().rstrip("=")

    @classmethod
    def parse(cls, s: str) -> "CausalContext":
        if not s:
            return cls()
        pad = "=" * ((-len(s)) % 4)
        try:
            buf = base64.urlsafe_b64decode(s + pad)
        except Exception as e:
            raise ValueError(f"invalid causality token: {e}")
        if len(buf) % 16 != 0:
            raise ValueError("invalid causality token length")
        vc = {}
        for i in range(0, len(buf), 16):
            n, t = struct.unpack(">QQ", buf[i : i + 16])
            vc[n] = t
        return cls(vc)

    def get(self, node: int) -> int:
        return self.vector_clock.get(node, 0)

    def advance(self, node: int, ts: int) -> None:
        self.vector_clock[node] = max(self.vector_clock.get(node, 0), ts)

    def is_newer_than(self, other: "CausalContext") -> bool:
        """True if self has seen anything other hasn't (ref
        causality.rs:100-110)."""
        return any(
            t > other.vector_clock.get(n, 0)
            for n, t in self.vector_clock.items()
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, CausalContext)
            and self.vector_clock == other.vector_clock
        )

    def __repr__(self) -> str:  # pragma: no cover
        return f"CausalContext({self.vector_clock})"
