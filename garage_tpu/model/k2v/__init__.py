"""K2V — the key/key/value store (ref src/model/k2v/, SURVEY.md §2.6).

Items are addressed (bucket, partition_key, sort_key) and hold a DVVS
(dotted version vector set) causal multi-value register: concurrent writes
from different nodes are all retained as conflicting values until a write
with a causal context covering them supersedes them.
"""

from .causality import CausalContext
from .item_table import DvvsEntry, DvvsValue, K2VItem, K2VItemTableSchema

__all__ = [
    "CausalContext",
    "DvvsEntry",
    "DvvsValue",
    "K2VItem",
    "K2VItemTableSchema",
]
