"""K2V RPC — insert routing and long-poll.

Equivalent of reference src/model/k2v/rpc.rs:42-571: writes are NOT
applied at the gateway — they are routed to one of the partition's storage
nodes, which assigns the timestamp inside a local transaction (from the
`k2v_local_timestamp` tree) and applies the DVVS update; this keeps vector
clocks to one entry per *storage* node rather than per gateway.  The
storage node then relies on the normal table quorum insert to spread the
result.  PollItem long-polls on a SubscriptionManager (k2v/sub.rs) woken
by the item table's updated() hook.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Dict, List, Optional, Tuple

from ...net.frame import PRIO_NORMAL
from ...rpc.rpc_helper import RequestStrategy
from ...table.schema import hash_partition_key
from ...utils.crdt import now_msec
from ...utils.data import Uuid
from ...utils.error import GarageError
from .causality import CausalContext
from .item_table import K2VItem

logger = logging.getLogger("garage_tpu.k2v.rpc")

TIMEOUT = 30.0


class SubscriptionManager:
    """Waiters on item updates (ref k2v/sub.rs:110): key → asyncio.Event
    fan-out; range waiters match on (bucket, partition) prefix."""

    def __init__(self):
        self._item_waiters: Dict[tuple, List[asyncio.Queue]] = {}
        self._range_waiters: Dict[tuple, List[asyncio.Queue]] = {}

    def subscribe_item(self, bucket_id, pk: str, sk: str) -> asyncio.Queue:
        q = asyncio.Queue()
        self._item_waiters.setdefault((bytes(bucket_id), pk, sk), []).append(q)
        return q

    def unsubscribe_item(self, bucket_id, pk: str, sk: str, q) -> None:
        ws = self._item_waiters.get((bytes(bucket_id), pk, sk), [])
        if q in ws:
            ws.remove(q)

    def subscribe_range(self, bucket_id, pk: str) -> asyncio.Queue:
        q = asyncio.Queue()
        self._range_waiters.setdefault((bytes(bucket_id), pk), []).append(q)
        return q

    def unsubscribe_range(self, bucket_id, pk: str, q) -> None:
        ws = self._range_waiters.get((bytes(bucket_id), pk), [])
        if q in ws:
            ws.remove(q)

    def notify(self, item: K2VItem) -> None:
        for q in self._item_waiters.get(
            (bytes(item.bucket_id), item.partition_key_str, item.sort_key_str), []
        ):
            q.put_nowait(item)
        for q in self._range_waiters.get(
            (bytes(item.bucket_id), item.partition_key_str), []
        ):
            q.put_nowait(item)


class K2VRpcHandler:
    def __init__(self, system, item_table, db, subscriptions: SubscriptionManager):
        self.system = system
        self.item_table = item_table
        self.subscriptions = subscriptions
        # per-partition monotonic timestamp source (ref rpc.rs:114+
        # k2v_local_timestamp tree)
        self.local_timestamp = db.open_tree("k2v_local_timestamp")
        self.endpoint = system.netapp.endpoint("garage/k2v")
        self.endpoint.set_handler(self._handle)

    # --- client side -------------------------------------------------------

    async def insert(
        self,
        bucket_id: Uuid,
        partition_key: str,
        sort_key: str,
        causal_context: Optional[CausalContext],
        value: Optional[bytes],
    ) -> None:
        """Route the write to a storage node of the partition
        (ref rpc.rs:75-110 insert)."""
        h = hash_partition_key((bytes(bucket_id), partition_key))
        who = self.system.rpc.request_order(
            self.item_table.replication.write_nodes(h)
        )
        msg = {
            "t": "insert",
            "b": bytes(bucket_id),
            "pk": partition_key,
            "sk": sort_key,
            "ct": causal_context.serialize() if causal_context else None,
            "v": value,
        }
        errs = []
        for node in who:
            try:
                await self.endpoint.call(node, msg, prio=PRIO_NORMAL, timeout=TIMEOUT)
                return
            except Exception as e:
                errs.append(str(e))
        raise GarageError(f"k2v insert failed on all nodes: {errs}")

    async def insert_many(
        self,
        bucket_id: Uuid,
        items: List[Tuple[str, str, Optional[CausalContext], Optional[bytes]]],
    ) -> None:
        """Batch insert grouped by routed node (ref rpc.rs insert_many)."""
        per_node: Dict[bytes, List] = {}
        for pk, sk, ct, v in items:
            h = hash_partition_key((bytes(bucket_id), pk))
            who = self.system.rpc.request_order(
                self.item_table.replication.write_nodes(h)
            )
            per_node.setdefault(bytes(who[0]), []).append(
                [pk, sk, ct.serialize() if ct else None, v]
            )

        async def send(node_b, batch):
            from ...utils.data import FixedBytes32

            await self.endpoint.call(
                FixedBytes32(node_b),
                {"t": "insert_many", "b": bytes(bucket_id), "items": batch},
                timeout=TIMEOUT,
            )

        results = await asyncio.gather(
            *[send(n, b) for n, b in per_node.items()], return_exceptions=True
        )
        # a node's whole batch failing (routed node down) falls back to
        # per-item inserts, which walk the remaining replicas — one dead
        # primary must not fail the batch
        retry = []
        for (node, batch), res in zip(per_node.items(), results):
            if isinstance(res, Exception):
                retry.extend(batch)
        if retry:
            errs = []
            for pk, sk, ct_ser, v in retry:
                try:
                    await self.insert(
                        bucket_id, pk, sk,
                        CausalContext.parse(ct_ser) if ct_ser else None, v,
                    )
                except Exception as e:  # noqa: BLE001 — collected below
                    errs.append(str(e))
            if errs:
                raise GarageError(f"k2v insert_many partial failure: {errs}")

    async def poll_item(
        self,
        bucket_id: Uuid,
        partition_key: str,
        sort_key: str,
        causal_context: CausalContext,
        timeout: float,
    ) -> Optional[K2VItem]:
        """Wait until the item advances past the given causality token
        (ref rpc.rs poll_item + k2v/sub.rs); polls replicas concurrently
        and returns the first advanced version, None on timeout."""
        h = hash_partition_key((bytes(bucket_id), partition_key))
        who = self.item_table.replication.read_nodes(h)
        msg = {
            "t": "poll_item",
            "b": bytes(bucket_id),
            "pk": partition_key,
            "sk": sort_key,
            "ct": causal_context.serialize(),
            "timeout": timeout,
        }

        async def ask(node):
            resp = await self.endpoint.call(
                node, msg, prio=PRIO_NORMAL, timeout=timeout + 10.0
            )
            if resp.get("item") is None:
                raise asyncio.TimeoutError()
            return self.item_table.data.decode_entry(bytes(resp["item"]))

        tasks = [asyncio.ensure_future(ask(n)) for n in who]
        try:
            done, pending = await asyncio.wait(
                tasks, timeout=timeout + 5.0,
                return_when=asyncio.FIRST_COMPLETED,
            )
            for t in done:
                if t.exception() is None:
                    return t.result()
            return None
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()

    # --- server side -------------------------------------------------------

    def _assign_timestamp(self, tx, pk_hash: bytes, proposed: int) -> int:
        """Monotonic per-partition timestamp (ref rpc.rs local timestamp
        tree): max(now, last+1)."""
        cur = tx.get(self.local_timestamp, pk_hash)
        last = struct.unpack(">Q", cur)[0] if cur is not None else 0
        ts = max(proposed, last + 1)
        tx.insert(self.local_timestamp, pk_hash, struct.pack(">Q", ts))
        return ts

    def _local_insert(self, bucket_id: bytes, pk: str, sk: str,
                      ct: Optional[str], value: Optional[bytes]) -> K2VItem:
        """Apply one write locally with a fresh timestamp, inside the item
        table's update transaction (ref rpc.rs handle_insert)."""
        context = CausalContext.parse(ct) if ct else None
        data = self.item_table.data
        h = hash_partition_key((bucket_id, pk))

        def update_fn(tx, old: Optional[K2VItem]) -> K2VItem:
            item = old if old is not None else K2VItem(Uuid(bucket_id), pk, sk)
            ts = self._assign_timestamp(tx, bytes(h), now_msec())
            item.update(bytes(self.system.id), context, value, ts=ts)
            return item

        new_item = data.update_entry_with((bucket_id, pk), sk, update_fn)
        if new_item is None:
            # no change (idempotent re-apply); read current
            raw = data.read_entry((bucket_id, pk), sk)
            new_item = data.decode_entry(raw)
        return new_item

    async def _handle(self, remote, msg, body):
        t = msg.get("t")
        if t == "insert":
            item = self._local_insert(
                bytes(msg["b"]), msg["pk"], msg["sk"], msg.get("ct"),
                bytes(msg["v"]) if msg.get("v") is not None else None,
            )
            # spread to the other replicas via the table quorum path
            await self.item_table.insert(item)
            return {"ok": True}, None
        if t == "insert_many":
            b = bytes(msg["b"])
            items = []
            for pk, sk, ct, v in msg["items"]:
                items.append(self._local_insert(
                    b, pk, sk, ct, bytes(v) if v is not None else None
                ))
            await self.item_table.insert_many(items)
            return {"ok": True}, None
        if t == "poll_item":
            item = await self._handle_poll(
                bytes(msg["b"]), msg["pk"], msg["sk"], msg["ct"],
                float(msg["timeout"]),
            )
            return {"item": item.encode() if item is not None else None}, None
        raise GarageError(f"unknown k2v rpc {t!r}")

    async def _handle_poll(self, bucket_id, pk, sk, ct, timeout) -> Optional[K2VItem]:
        context = CausalContext.parse(ct)
        data = self.item_table.data
        # subscribe FIRST to avoid a notify/check race (ref sub.rs)
        q = self.subscriptions.subscribe_item(bucket_id, pk, sk)
        try:
            raw = data.read_entry((bucket_id, pk), sk)
            if raw is not None:
                item = data.decode_entry(raw)
                if item.causal_context().is_newer_than(context):
                    return item
            deadline = time.monotonic() + timeout
            while True:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return None
                try:
                    item = await asyncio.wait_for(q.get(), timeout=remain)
                except asyncio.TimeoutError:
                    return None
                if item.sort_key_str == sk and item.causal_context().is_newer_than(context):
                    return item
        finally:
            self.subscriptions.unsubscribe_item(bucket_id, pk, sk, q)
