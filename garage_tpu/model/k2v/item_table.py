"""K2V item table — DVVS causal multi-value registers.

Equivalent of reference src/model/k2v/item_table.rs:17-223: an item is
keyed P = (bucket uuid, partition key string), S = sort key, and stores a
map writer-node(u64) → DvvsEntry { t_discard, [(ts, value|deleted)] }.
An insert with causal context C discards, per writer, the values C covers
(t ≤ C[writer]) and adds one new (ts, value) under the inserting node; the
CRDT merge keeps the max t_discard and the union of surviving values — so
causally-ordered writes replace, concurrent writes become siblings.
Counters: items / conflicts / values / bytes per bucket partition.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ...table.schema import Entry, TableSchema
from ...utils.data import Uuid
from .causality import CausalContext, node_id64

ENTRIES = "items"
CONFLICTS = "conflicts"
VALUES = "values"
BYTES = "bytes"


class DvvsValue:
    """Value(bytes) | Deleted — encoded as bytes or None."""

    DELETED = None


class DvvsEntry:
    """Per-writer-node state (ref item_table.rs DvvsEntry)."""

    __slots__ = ("t_discard", "values")

    def __init__(self, t_discard: int = 0, values: Optional[List[Tuple[int, Optional[bytes]]]] = None):
        self.t_discard = t_discard
        # [(timestamp, value-bytes | None=deleted)], ts strictly > t_discard
        self.values = values or []

    def max_time(self) -> int:
        return max([self.t_discard] + [t for t, _v in self.values])

    def discard_up_to(self, t: int) -> None:
        if t > self.t_discard:
            self.t_discard = t
            self.values = [(ts, v) for ts, v in self.values if ts > t]

    def merge(self, other: "DvvsEntry") -> None:
        td = max(self.t_discard, other.t_discard)
        merged = {(ts, v if v is None else bytes(v)) for ts, v in self.values}
        merged |= {(ts, v if v is None else bytes(v)) for ts, v in other.values}
        self.t_discard = td
        self.values = sorted(
            [(ts, v) for ts, v in merged if ts > td],
            key=lambda x: (x[0], x[1] is not None, x[1] or b""),
        )

    def pack(self) -> Any:
        return [self.t_discard, [[t, v] for t, v in self.values]]

    @classmethod
    def unpack(cls, b: Any) -> "DvvsEntry":
        return cls(int(b[0]), [(int(t), bytes(v) if v is not None else None) for t, v in b[1]])


class K2VItem(Entry):
    VERSION_MARKER = b"GT01k2vitem"

    def __init__(
        self,
        bucket_id: Uuid,
        partition_key: str,
        sort_key: str,
        items: Optional[Dict[int, DvvsEntry]] = None,
    ):
        self.bucket_id = bucket_id
        self.partition_key_str = partition_key
        self.sort_key_str = sort_key
        self.items: Dict[int, DvvsEntry] = items or {}

    @property
    def partition_key(self) -> tuple:
        # composite partition (ref item_table.rs K2VItemPartition)
        return (bytes(self.bucket_id), self.partition_key_str)

    @property
    def sort_key(self) -> str:
        return self.sort_key_str

    # --- DVVS ops (ref item_table.rs:60-130) ---

    def causal_context(self) -> CausalContext:
        return CausalContext({n: e.max_time() for n, e in self.items.items()})

    def update(
        self,
        this_node: bytes,
        context: Optional[CausalContext],
        value: Optional[bytes],
        ts: Optional[int] = None,
    ) -> int:
        """Apply one insert/delete at this writer node; returns the
        timestamp assigned (ref item_table.rs:75-106)."""
        if context is not None:
            for n, t_seen in context.vector_clock.items():
                e = self.items.get(n)
                if e is not None:
                    e.discard_up_to(t_seen)
        n64 = node_id64(this_node)
        e = self.items.setdefault(n64, DvvsEntry())
        if ts is None:
            ts = e.max_time() + 1
        ts = max(ts, e.max_time() + 1)
        e.values.append((ts, value if value is None else bytes(value)))
        return ts

    def values(self) -> List[Optional[bytes]]:
        """All surviving values (None = delete marker sibling), sorted for
        determinism."""
        out = []
        for _n, e in sorted(self.items.items()):
            out.extend(v for _t, v in e.values)
        return out

    def live_values(self) -> List[bytes]:
        return [v for v in self.values() if v is not None]

    def is_tombstone(self) -> bool:
        # every surviving sibling is a delete marker (ref item_table.rs
        # is_tombstone: all values Deleted)
        return all(v is None for v in self.values())

    def merge(self, other: "K2VItem") -> None:
        for n, e in other.items.items():
            mine = self.items.get(n)
            if mine is None:
                self.items[n] = DvvsEntry(e.t_discard, list(e.values))
            else:
                mine.merge(e)

    def counts(self) -> List[Tuple[str, int]]:
        """ref item_table.rs:480+ counted item."""
        vals = self.values()
        live = [v for v in vals if v is not None]
        ent = 1 if live else 0
        return [
            (ENTRIES, ent),
            (CONFLICTS, 1 if len(vals) > 1 else 0),
            (VALUES, len(live)),
            (BYTES, sum(len(v) for v in live)),
        ]

    def fields(self) -> Any:
        return [
            bytes(self.bucket_id),
            self.partition_key_str,
            self.sort_key_str,
            [[n, e.pack()] for n, e in sorted(self.items.items())],
        ]

    @classmethod
    def from_fields(cls, b: Any) -> "K2VItem":
        return cls(
            Uuid(bytes(b[0])), b[1], b[2],
            {int(n): DvvsEntry.unpack(e) for n, e in b[3]},
        )


class K2VItemTableSchema(TableSchema):
    TABLE_NAME = "k2v_item"
    ENTRY = K2VItem

    def __init__(self, counter=None, subscriptions=None):
        self.counter = counter
        self.subscriptions = subscriptions

    def updated(self, tx, old: Optional[K2VItem], new: Optional[K2VItem]) -> None:
        it = old or new
        if self.counter is not None:
            self.counter.count(
                tx,
                bytes(it.bucket_id),
                it.partition_key_str,
                old.counts() if old is not None else [],
                new.counts() if new is not None else [],
            )
        if self.subscriptions is not None and new is not None:
            # wake long-polls after commit (ref k2v/rpc.rs local_insert →
            # subscription notify)
            tx.on_commit(lambda: self.subscriptions.notify(new))

    def matches_filter(self, entry: K2VItem, filter: Any) -> bool:
        from ...table.schema import DeletedFilter

        has_value = bool(entry.live_values())
        if filter is None:
            return has_value
        if filter == "conflicts_only":
            return len(entry.values()) > 1
        return DeletedFilter.matches(filter, not has_value)
