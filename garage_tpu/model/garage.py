"""Garage — the god object wiring every subsystem of one node.

Equivalent of reference src/model/garage.rs:36-379 (SURVEY.md §2.6):
opens the metadata DB engine, builds `System` (membership/ring/rpc), the
three replication parameter sets (data: read quorum 1; meta: read+write
quorums; control: full copy — garage.rs:231-248), the BlockManager +
resync manager, and all replicated tables with their cross-table
`updated()` hooks (object → version → block_ref → rc), then spawns all
background workers (garage.rs:358-379).
"""

from __future__ import annotations

import asyncio
import logging
import os
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

from ..block.manager import BlockManager
from ..block.repair import RebalanceWorker, RepairWorker, ScrubWorker, ScrubWorkerState
from ..block.resync import (
    MAX_RESYNC_WORKERS,
    BlockResyncManager,
    ResyncPersistedConfig,
    ResyncWorker,
)
from ..db import Db, open_db
from ..rpc.replication_mode import parse_replication_mode
from ..rpc.system import System
from ..table import (
    InsertQueueWorker,
    MerkleWorker,
    Table,
    TableFullReplication,
    TableGc,
    TableShardedReplication,
    TableSyncer,
)
from ..utils.background import BackgroundRunner, BgVars
from ..utils.config import Config
from ..utils.persister import Persister
from .bucket_alias_table import BucketAliasTableSchema
from .bucket_table import BucketTableSchema
from .index_counter import IndexCounter, counter_table_schema
from .key_table import KeyTableSchema
from .s3.block_ref_table import BlockRefTableSchema
from .s3.mpu_table import MpuTableSchema
from .s3.object_table import ObjectTableSchema
from .s3.version_table import VersionTableSchema

logger = logging.getLogger("garage_tpu.model.garage")


class Garage:
    """ref model/garage.rs:36-77."""

    def __init__(self, config: Config, db: Optional[Db] = None):
        self.config = config
        self.replication_mode = parse_replication_mode(config.replication_mode)
        # Optional asymmetric durability (the erasure-coded storage
        # class): metadata tables keep replication_mode, while BLOCK
        # placement uses data_replication_mode — e.g. meta "3" + data
        # "none" + codec.parity_distribute stores 1× data + m/k parity
        # (1.5× total at RS(8,4)) yet survives the loss of any m
        # codeword nodes, where the reference can only trade whole
        # replicas (replication_mode.rs:41-56, 3× for 2-loss).
        self.data_replication_mode = (
            parse_replication_mode(config.data_replication_mode)
            if config.data_replication_mode else self.replication_mode
        )

        os.makedirs(config.metadata_dir, exist_ok=True)
        self._owns_db = db is None
        if db is not None:
            self.db = db
        else:
            is_native = config.db_engine in ("native", "logdb")
            is_memory = config.db_engine in ("memory", "mem")
            kw = ({"fsync": config.metadata_fsync}
                  if (is_native or is_memory) else {})
            # the memory engine is DURABLE when the daemon opens it
            # (snapshot + WAL under metadata_dir — the sled slot);
            # RAM-only remains available to tests via open_db("memory")
            # with no path
            fname = ("db.logdb" if is_native
                     else "db.mem" if is_memory else "db.sqlite")
            self.db = open_db(
                config.db_engine,
                path=os.path.join(config.metadata_dir, fname),
                **kw,
            )

        self.system = System(config, self.replication_mode)

        factor = self.replication_mode.replication_factor
        # ref garage.rs:231-248: data reads need only one copy (content-
        # addressed, self-verifying); metadata reads/writes use quorums;
        # control tables (buckets/keys/aliases) are fully replicated
        self.data_rep = TableShardedReplication(
            self.system,
            self.data_replication_mode.replication_factor,
            1,
            self.data_replication_mode.write_quorum,
        )
        self.meta_rep = TableShardedReplication(
            self.system,
            factor,
            self.replication_mode.read_quorum,
            self.replication_mode.write_quorum,
        )
        self.control_rep = TableFullReplication(self.system)

        self.block_manager = BlockManager(
            config, self.db, self.system, self.data_rep
        )
        self.block_resync = BlockResyncManager(
            self.block_manager, self.db,
            persister=Persister(
                config.metadata_dir, "resync_cfg", ResyncPersistedConfig
            ),
        )
        self.block_manager.resync = self.block_resync
        # crash-consistency pass over the data dirs, AFTER resync is
        # attached (quarantined hashes re-enqueue through it): purge
        # orphaned .tmp files from torn writes, bound the .corrupted
        # quarantine (docs/ROBUSTNESS.md "Disk faults & degraded mode")
        self.block_manager.startup_janitor()
        if config.codec.store_parity and config.codec.rs_data > 0:
            from ..block.parity import ParityStore, WriteParityAccumulator

            self.block_manager.parity_store = ParityStore(
                self.block_manager, self.db, self.block_manager.codec
            )
            if config.codec.parity_on_write:
                # BASELINE config #3: RS encode on the PutObject path —
                # parity exists from first write, not from the first
                # scrub pass (encoding itself runs off the write path)
                self.block_manager.write_parity = WriteParityAccumulator(
                    self.block_manager.parity_store,
                    self.block_manager.codec,
                )

        # --- tables, wired bottom-up so hooks can reach lower tables ---
        self.bucket_table = Table(
            self.system, BucketTableSchema(), self.control_rep, self.db
        )
        self.bucket_alias_table = Table(
            self.system, BucketAliasTableSchema(), self.control_rep, self.db
        )
        self.key_table = Table(
            self.system, KeyTableSchema(), self.control_rep, self.db
        )

        self.object_counter_table = Table(
            self.system,
            counter_table_schema("bucket_object_counter"),
            self.meta_rep,
            self.db,
        )
        self.object_counter = IndexCounter(
            self.system, self.object_counter_table, self.db
        )
        self.mpu_counter_table = Table(
            self.system,
            counter_table_schema("bucket_mpu_counter"),
            self.meta_rep,
            self.db,
        )
        self.mpu_counter = IndexCounter(
            self.system, self.mpu_counter_table, self.db
        )

        block_ref_schema = BlockRefTableSchema(self.block_manager)
        self.block_ref_table = Table(
            self.system, block_ref_schema, self.meta_rep, self.db
        )

        # cross-node parity: index sharded by member hash at META
        # replication (the index must outlive data-node loss), parity
        # shards stored as ordinary ring-placed blocks
        from .parity_index_table import ParityIndexTableSchema

        self.parity_index_table = Table(
            self.system, ParityIndexTableSchema(self.block_ref_table),
            self.meta_rep, self.db,
        )
        if config.codec.parity_distribute and config.codec.rs_data > 0:
            from ..block.parity import (
                ParityDistributor,
                WriteParityAccumulator,
            )
            from .parity_repair import make_parity_reconstructor

            # writer-side accumulator: distinct-node codewords, parity
            # distributed cross-node (independent of the storing-side
            # local-sidecar accumulator above)
            self.block_manager.ec_accumulator = WriteParityAccumulator(
                None, self.block_manager.codec,
                distributor=ParityDistributor(
                    self.block_manager, self.parity_index_table
                ),
                manager=self.block_manager,
            )
            from .parity_repair import make_parity_gc

            self.block_manager.parity_reconstructor = (
                make_parity_reconstructor(self)
            )
            # GC rides the GLOBAL deletion signal (last live version-ref
            # tombstoned), never local/migration deletes
            block_ref_schema.on_ref_dropped = make_parity_gc(self)
            self._want_parity_sweeper = True

        version_schema = VersionTableSchema(self.block_ref_table)
        self.version_table = Table(
            self.system, version_schema, self.meta_rep, self.db
        )

        mpu_schema = MpuTableSchema(self.version_table, self.mpu_counter)
        self.mpu_table = Table(self.system, mpu_schema, self.meta_rep, self.db)

        object_schema = ObjectTableSchema(
            self.version_table, self.mpu_table, self.object_counter
        )
        self.object_table = Table(
            self.system, object_schema, self.meta_rep, self.db
        )

        # --- K2V (ref garage.rs k2v section + model/k2v/) ---
        from .k2v.item_table import K2VItemTableSchema
        from .k2v.rpc import K2VRpcHandler, SubscriptionManager

        self.k2v_counter_table = Table(
            self.system,
            counter_table_schema("k2v_index_counter"),
            self.meta_rep,
            self.db,
        )
        self.k2v_counter = IndexCounter(
            self.system, self.k2v_counter_table, self.db
        )
        self.k2v_subscriptions = SubscriptionManager()
        k2v_schema = K2VItemTableSchema(self.k2v_counter, self.k2v_subscriptions)
        self.k2v_item_table = Table(
            self.system, k2v_schema, self.meta_rep, self.db
        )
        self.k2v_rpc = K2VRpcHandler(
            self.system, self.k2v_item_table, self.db, self.k2v_subscriptions
        )

        self.tables: List[Table] = [
            self.bucket_table,
            self.bucket_alias_table,
            self.key_table,
            self.object_counter_table,
            self.mpu_counter_table,
            self.block_ref_table,
            self.parity_index_table,
            self.version_table,
            self.mpu_table,
            self.object_table,
            self.k2v_counter_table,
            self.k2v_item_table,
        ]

        # --- overload protection (docs/ROBUSTNESS.md "Overload &
        # brownout"): the front-door admission gate and the background
        # load governor, wired to the live pressure signals this node
        # already produces ---
        from ..api.admission import AdmissionGate, RemotePressureProbe
        from ..utils.overload import LoadGovernor

        self.admission = AdmissionGate(config.api, metrics=self.system.metrics)
        self.governor = LoadGovernor(config.api, metrics=self.system.metrics)
        self.governor.add_signal("admission", self.admission.occupancy)
        # the Retry-After hint on sheds tracks live pressure, not a
        # constant; gossip carries the same signal to remote gateways
        # (cluster-aware admission) and the probe folds the gossiped
        # pressure of a request's placement nodes back into this node's
        # own front door
        self.admission.pressure_fn = self.governor.pressure
        self.system.governor_pressure_fn = self.governor.pressure
        self.admission_probe = RemotePressureProbe(self.system)
        feeder = self.block_manager.feeder
        if feeder is not None:
            depth_full = max(config.api.governor_feeder_depth_full, 1)
            self.governor.add_signal(
                "feeder_depth",
                lambda: len(feeder._pending) / depth_full)
        health = getattr(self.block_manager, "health", None)
        if health is not None:
            # a sick disk is mild pressure (scrub/resync hammering a
            # degraded root steals the IO the foreground needs) — but
            # CAPPED below governor_high on purpose: a disk can stay
            # failed for days awaiting replacement, and parking ALL
            # background work at min_ratio for that long would throttle
            # the very re-replication that restores redundancy.  Disk
            # state alone therefore throttles partially, never fully;
            # only live foreground signals can drive the ratio to the
            # floor.
            _disk_p = {"ok": 0.0, "degraded": 0.5, "failed": 0.5}
            self.governor.add_signal(
                "disk", lambda: _disk_p.get(health.worst_state(), 0.0))
        # netapp write loops feed per-frame queue waits (HOL pressure)
        self.system.netapp.queue_wait_hook = self.governor.note_queue_wait
        # repair-storm fetch concurrency clamps against the same ratio
        self.block_manager.governor = self.governor
        # the device transport demotes background batches against the
        # same ratio (survives a late async device attach)
        codec = self.block_manager.codec
        if hasattr(codec, "set_governor"):
            codec.set_governor(self.governor.ratio)

        # --- fleet health plane (docs/OBSERVABILITY.md "Fleet health &
        # SLOs"): the SLO burn-rate engine fed by the API front doors,
        # and the incident flight recorder its fast-burn breaches (plus
        # fail-slow flips and disk/cluster degradation) trigger ---
        from ..utils.flightrec import FlightRecorder
        from ..utils.slo import SloTracker

        self.flightrec = FlightRecorder(
            os.path.join(config.metadata_dir, "incidents"),
            node_id=bytes(self.system.id).hex()[:16],
            max_bundles=getattr(config, "incident_max_bundles", 16),
            debounce_s=getattr(config, "incident_debounce_secs", 60.0),
            metrics=self.system.metrics,
        )
        self.slo = SloTracker(
            getattr(config, "slo", None), metrics=self.system.metrics,
            on_fast_burn=lambda ep, slo, burn: self.flightrec.trigger(
                "slo_fast_burn",
                {"endpoint": ep, "slo": slo, "burn": round(burn, 2)}),
        )
        self._wire_flight_recorder()

        # --- continuous CPU profiler (docs/OBSERVABILITY.md "CPU
        # attribution"): always-on thread-stack sampler joined to the
        # waterfall segment taxonomy.  Constructed here so its metric
        # families live on this node's registry; started alongside the
        # workers (spawn_workers) and stopped in shutdown() ---
        from ..utils.cpuprof import CpuProfiler

        self.cpuprof = CpuProfiler(
            metrics=self.system.metrics,
            hz=float(getattr(config, "cpuprof_hz", 29.0)))
        self.flightrec.add_collector(
            "cpu_profile",
            lambda: self.cpuprof.flight_recorder_section())

        self.bg = BackgroundRunner()
        # background workers duty-cycle against foreground pressure
        self.bg.governor = self.governor
        self.bg_vars = BgVars()
        self.scrub_worker: Optional[ScrubWorker] = None

    def _wire_flight_recorder(self) -> None:
        """Collectors (what a bundle contains) + auto-triggers (when one
        is captured).  Everything here is a SYNC snapshot of state the
        node already holds — a capture must never wait on the network;
        cross-node context comes from the gossip tables."""
        fr = self.flightrec
        sys_ = self.system
        mgr = self.block_manager

        fr.add_collector("metrics", lambda: sys_.metrics.render())
        fr.add_collector("slo", lambda: self.slo.status())

        def _waterfalls():
            wf = getattr(sys_.tracer, "waterfall", None)
            if wf is None:
                return None
            return {"endpoints": wf.endpoints(), "retained": wf.entries()}

        fr.add_collector("waterfalls", _waterfalls)
        fr.add_collector(
            "device_timeline",
            lambda: mgr.codec.obs.timeline.chrome_trace(2048))
        fr.add_collector(
            "gate_events", lambda: mgr.codec.obs.events_list(128))

        def _pool_stats():
            # device-resident block pool (ops/device_pool.py): residency,
            # hit/miss byte split and eviction counters — an incident on
            # a device-armed node needs to show whether the warm path
            # was actually warm when things went sideways
            pool = getattr(mgr.codec, "pool", None)
            return pool.stats() if pool is not None else None

        fr.add_collector("device_pool", _pool_stats)
        fr.add_collector("slow_ops", lambda: sys_.tracer.slow.snapshot(32))

        fr.add_collector("peers", lambda: [
            sys_.peer_core_row(nid, st)
            for nid, st in sys_.peering.peers.items()
        ])
        fr.add_collector("governor", lambda: {
            "pressure": round(self.governor.pressure(), 4),
            "ratio": round(self.governor.ratio(), 4),
            "signals": self.governor.signals(),
        })
        fr.add_collector("disk", lambda: {
            "states": mgr.health.states(),
            "worst": mgr.health.worst_state(),
            "error_counts": {f"{op}:{kind}": n for (op, kind), n in
                             dict(mgr.health.error_counts).items()},
            "quarantined": mgr.quarantined,
        })
        fr.add_collector("heals", lambda: dict(mgr.heal_counts))
        fr.add_collector("resync_enqueues", lambda: (
            dict(mgr.resync.enqueue_counts)
            if mgr.resync is not None else None))
        fr.add_collector("admission", lambda: {
            "occupancy": round(self.admission.occupancy(), 4),
            "retry_after_hint": self.admission.retry_after_hint(),
        })

        def _cluster():
            h = sys_.health()
            return {"status": h.status,
                    "connected_nodes": h.connected_nodes,
                    "known_nodes": h.known_nodes,
                    "partitions_quorum": h.partitions_quorum,
                    "partitions": h.partitions}

        fr.add_collector("cluster_health", _cluster)

        # auto-trigger: fail-slow flag transitions (the scorer runs on
        # the gossip cadence; a flip means the fleet just gained or
        # healed a straggler — snapshot the evidence either way)
        sys_.health_scorer.on_change = (
            lambda peer, flagged, score: fr.trigger(
                "fail_slow_set" if flagged else "fail_slow_clear",
                {"peer": peer, "score": score}))

        # auto-trigger: disk / cluster (zone) state degradation, watched
        # on the status-gossip cadence.  Only DEGRADATIONS capture —
        # recovery is good news and the degradation bundle already holds
        # the interesting state
        disk_rank = {"ok": 0, "degraded": 1, "failed": 2}
        cluster_rank = {"healthy": 0, "degraded": 1, "unavailable": 2}
        # baselines initialize from the FIRST observation, not an
        # assumed-healthy state: a booting node is "unavailable" until
        # the mesh connects, and that startup transient must not write
        # a bundle (and eat the debounce window) on every boot
        watch: dict = {}

        def _degradation_watch():
            d = mgr.health.worst_state()
            if ("disk" in watch
                    and disk_rank.get(d, 0) > disk_rank.get(watch["disk"], 0)):
                fr.trigger("disk_degraded", {"state": d})
            watch["disk"] = d
            c = sys_.health().status
            if ("cluster" in watch
                    and cluster_rank.get(c, 0) > cluster_rank.get(
                        watch["cluster"], 0)):
                fr.trigger("cluster_degraded", {"status": c})
            watch["cluster"] = c

        sys_.status_tick_hooks.append(_degradation_watch)

    # --- workers (ref garage.rs:358-379, block/manager.rs:192-227) ---

    def spawn_workers(self) -> None:
        # the node is going live: start the always-on CPU sampler and
        # register this (event-loop) thread so its samples join to the
        # running task's span segment
        from ..utils import cpuprof as _cpuprof

        try:
            _cpuprof.register_loop()
        except RuntimeError:
            pass  # no running loop (sync harnesses): worker roles still join
        else:
            # the to_thread pool (stream digests, zstd, direct-io
            # writes, sqlite scans) is long-lived once spawned: give it
            # a named, role-registered executor so its samples don't
            # fold under other;other.  First Garage on the loop wins;
            # asyncio.run's shutdown_default_executor reaps it.
            loop = asyncio.get_running_loop()
            if getattr(loop, "_default_executor", None) is None:
                loop.set_default_executor(ThreadPoolExecutor(
                    thread_name_prefix="aio-worker",
                    initializer=lambda:
                        _cpuprof.register_thread("aio-worker")))
        self.cpuprof.start()
        for t in self.tables:
            # batched Merkle hashing rides the codec feeder's ragged
            # mhash path (class bg) — the trie drain shares the data
            # plane's batching engine instead of hashing node-at-a-time
            t.merkle.feeder = self.block_manager.feeder
            t.syncer = TableSyncer(self.system, t.data, t.merkle)
            t.gc = TableGc(self.system, t.data)
            self.bg.spawn(MerkleWorker(t.merkle))
            # make_worker (NOT a bare SyncWorker): it attaches the worker
            # to the syncer (admin `repair tables` drives it) and hooks
            # on_ring_change so a layout change triggers immediate
            # re-sync + partition offload (ref sync.rs:589-601) instead
            # of waiting for the anti-entropy timer
            self.bg.spawn(t.syncer.make_worker())
            self.bg.spawn(t.gc.make_worker())
            self.bg.spawn(InsertQueueWorker(t))
        # Spawn the max worker count; the active number is the runtime-
        # tunable persisted `n_workers` — idle extras cost one sleeping
        # task each (ref resync.rs:481-567 + block/manager.rs:209-227).
        for i in range(MAX_RESYNC_WORKERS):
            self.bg.spawn(ResyncWorker(self.block_resync, index=i))
        self.scrub_worker = ScrubWorker(
            self.block_manager,
            persister=Persister(
                self.config.metadata_dir, "scrub_info", ScrubWorkerState
            ),
        )
        self.bg.spawn(self.scrub_worker)
        # Automatic post-layout-change block sweep: a ring change fires no
        # table hook, so a node that gained the data assignment for an
        # already-referenced block (rc>0 — no 0→1 incref will ever come)
        # would hold a hole until an operator ran `repair blocks`.  The
        # refs-only RepairWorker re-enqueues every referenced hash; the
        # resync logic then fetches gained blocks / offloads lost ones.
        # Debounced: a sweep still in flight is rewound, not duplicated
        # (layout propagation delivers several ring changes in a burst).
        # The swept ring digest persists ON COMPLETION: a node that was
        # down for the change (its boot merge sees changed=False, so no
        # callback ever fires) or crashed mid-sweep finds a stale marker
        # here and re-sweeps at startup.
        from ..block.repair import LayoutSweepMarker

        self._layout_sweep = None
        self._layout_sweep_wid = None
        self._sweep_reap_backlog: list = []
        self._sweep_persister = Persister(
            self.config.metadata_dir, "layout_sweep", LayoutSweepMarker)

        def _spawn_sweep():
            if self._layout_sweep is not None and \
                    not self._layout_sweep.finished:
                self._layout_sweep.restart()
                return
            if self._layout_sweep_wid is not None:
                # recurring one-shot: drop the previous completed sweep's
                # registry entry or they accumulate across layout changes.
                # reap() can refuse in the narrow window where the sweep
                # set finished=True but its runner task hasn't returned
                # yet (advisor r4) — keep refused wids in a backlog and
                # retry them on every later spawn instead of leaking.
                self._sweep_reap_backlog.append(self._layout_sweep_wid)
                self._layout_sweep_wid = None
            self._sweep_reap_backlog = [
                wid for wid in self._sweep_reap_backlog
                if not self.bg.reap(wid)
            ]
            self._layout_sweep = RepairWorker(
                self.block_manager, refs_only=True,
                on_done=lambda: self._sweep_persister.save(
                    LayoutSweepMarker(self.system.ring.digest())),
            )
            self._layout_sweep_wid = self.bg.spawn(self._layout_sweep)

        self.system.on_ring_change(lambda _ring: _spawn_sweep())
        marker = self._sweep_persister.load()
        if self.system.ring.digest() != (marker.digest if marker else b""):
            _spawn_sweep()
        # Layout-change rebalance mover: the foreground, rate-bounded,
        # observable companion to the sweep above — walks ONLY the
        # partitions whose replica set changed (diffed here against the
        # previous ring) and drives their blocks through the resync
        # convergence step directly, reporting rebalance_partitions_*
        # progress.  The sweep remains the completeness backstop (it
        # also covers changes missed while down, via the marker).
        from ..block.rebalance import RebalanceMover
        from ..rpc.layout import N_PARTITIONS

        self.rebalance_mover = RebalanceMover(
            self.block_manager, self.block_resync,
            rate_mib_s=self.config.rebalance_rate_mib,
            metrics=self.system.metrics,
            governor=self.governor,
        )
        self.bg.spawn(self.rebalance_mover)

        def _part_sets(ring):
            return [frozenset(bytes(n) for n in ring.partition_nodes(p))
                    for p in range(N_PARTITIONS)]

        self._prev_partitions = _part_sets(self.system.ring)

        def _feed_mover(ring):
            new = _part_sets(ring)
            changed = [p for p in range(N_PARTITIONS)
                       if new[p] != self._prev_partitions[p]]
            self._prev_partitions = new
            if changed:
                self.rebalance_mover.enqueue(changed)

        self.system.on_ring_change(_feed_mover)
        # Fleet rebuild scheduler: when a ring change REMOVES a node
        # from the cluster (full-node loss, not a mere reshuffle), the
        # partitions that lost it are planned as one paced, checkpointed
        # rebuild flow (block/rebuild.py) — chain repair per codeword,
        # rotated tree roots, resync dedupe via `owns`.  The mover and
        # layout sweep still run for the same partitions; the owns()
        # seam keeps the three from double-repairing a block.
        from ..block.rebuild import RebuildCheckpoint, RebuildScheduler
        from .parity_repair import lookup_index_entries, try_codeword

        self.rebuild_scheduler = RebuildScheduler(
            self.block_manager, self.block_resync,
            rate_mib_s=self.config.rebuild_rate_mib,
            persister=Persister(
                self.config.metadata_dir, "rebuild_sched",
                RebuildCheckpoint),
            metrics=self.system.metrics,
            governor=self.governor,
            lookup=lambda h: lookup_index_entries(self, h, sweep=True),
            decode_fallback=lambda h, ent: try_codeword(self, h, ent),
        )
        self.bg.spawn(self.rebuild_scheduler)
        self.block_resync.rebuild = self.rebuild_scheduler
        self._prev_ring_nodes = frozenset(
            n for s in self._prev_partitions for n in s)

        self._rebuild_prev_sets = list(self._prev_partitions)

        def _feed_rebuild(ring):
            prev_sets = self._rebuild_prev_sets
            new = _part_sets(ring)
            self._rebuild_prev_sets = new
            new_nodes = frozenset(n for s in new for n in s)
            lost = self._prev_ring_nodes - new_nodes
            self._prev_ring_nodes = new_nodes
            if not lost:
                return  # reshuffle, not a node loss: mover's job alone
            me = bytes(self.system.id)
            # partitions that LOST one of the dead nodes and still
            # assign this node — the rows we are now responsible for
            mine = [p for p in range(N_PARTITIONS)
                    if me in new[p] and prev_sets[p] & lost]
            if mine:
                self.rebuild_scheduler.node_lost(mine, ring.digest())

        self.system.on_ring_change(_feed_rebuild)
        self.rebuild_scheduler.maybe_resume(self.system.ring.digest())
        self.bg_vars.register_ro(
            "rebuild-progress",
            lambda: (f"{self.rebuild_scheduler.partitions_done}/"
                     f"{self.rebuild_scheduler.partitions_total}"),
        )
        self.bg_vars.register_rw(
            "resync-tranquility",
            lambda: self.block_resync.tranquility,
            self.block_resync.set_tranquility,
        )
        self.bg_vars.register_rw(
            "resync-worker-count",
            lambda: self.block_resync.n_workers,
            self.block_resync.set_n_workers,
        )
        self.bg_vars.register_rw(
            "scrub-tranquility",
            lambda: self.scrub_worker.state.tranquility,
            self.scrub_worker.set_tranquility,
        )
        from .s3.lifecycle_worker import LifecycleWorker, LifecycleWorkerPersisted

        self.lifecycle_worker = LifecycleWorker(
            self,
            Persister(
                self.config.metadata_dir, "lifecycle_worker_state",
                LifecycleWorkerPersisted,
            ),
        )
        self.bg.spawn(self.lifecycle_worker)
        if getattr(self, "_want_parity_sweeper", False):
            from .parity_repair import ParityGcSweeper

            self.parity_gc_sweeper = ParityGcSweeper(self)
            self.bg.spawn(self.parity_gc_sweeper)
        self.bg_vars.register_ro(
            "lifecycle-last-completed",
            lambda: (
                self.lifecycle_worker.last_completed.isoformat()
                if self.lifecycle_worker.last_completed else "never"
            ),
        )

    def helper(self):
        from .helper import GarageHelper

        return GarageHelper(self)

    async def run(self) -> None:
        await self.system.run()

    async def shutdown(self) -> None:
        # flush partial write-time codewords before workers stop
        if self.block_manager.write_parity is not None:
            await self.block_manager.write_parity.drain()
        if self.block_manager.ec_accumulator is not None:
            await self.block_manager.ec_accumulator.drain()
        # post-decode heals would fail noisily against the closing RPC
        # layer; their persistent resync entries finish the job later
        self.block_manager.drain_heals()
        # codec feeder: refuse new submissions and drain accepted ones
        # (acked foreground work must complete; racing late submitters
        # fall back to direct codec calls via the *_or_direct helpers)
        if self.block_manager.feeder is not None:
            import asyncio

            await asyncio.to_thread(self.block_manager.feeder.shutdown)
        # device transport: drain staged/queued device batches (its
        # worker falls back to CPU inline if the device died mid-drain)
        codec = self.block_manager.codec
        if hasattr(codec, "close"):
            import asyncio

            await asyncio.to_thread(codec.close)
        # quorum-write stragglers and cancelled-read losers still talk
        # through the transport: give them a bounded drain BEFORE workers
        # and the netapp go away (System.shutdown drains again, cheaply,
        # for anything spawned in between)
        await self.system.rpc.shutdown(timeout=5.0)
        await self.bg.shutdown()
        self.cpuprof.stop()
        tracer = getattr(self.system, "tracer", None)
        if tracer is not None:
            await tracer.stop()  # final span flush before the node exits
            if tracer.exporter is not None:
                await tracer.exporter.close()
        await self.system.shutdown()
        if self._owns_db:
            self.db.close()
