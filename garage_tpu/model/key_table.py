"""API key table.

Equivalent of reference src/model/key_table.rs (SURVEY.md §2.6): keys are
`Deletable<KeyParams>` with an immutable secret, LWW name/allow-create
flags, per-bucket permission map, and per-key local bucket aliases.
Fully replicated (control data).
"""

from __future__ import annotations

import secrets
from typing import Any, Optional

from ..table.schema import Entry, TableSchema
from ..utils.crdt import Crdt, Deletable, Lww, LwwMap
from ..utils.data import Uuid
from .permission import BucketKeyPerm


def generate_key_id() -> str:
    """ref key_table.rs:180-186 — 'GK' + 12 hex bytes."""
    return "GK" + secrets.token_hex(12)


def generate_secret_key() -> str:
    return secrets.token_hex(32)


class KeyParams(Crdt):
    """ref key_table.rs:23-90."""

    __slots__ = ("secret_key", "name", "allow_create_bucket", "authorized_buckets", "local_aliases")

    def __init__(
        self,
        secret_key: str,
        name: Optional[Lww] = None,
        allow_create_bucket: Optional[Lww] = None,
        authorized_buckets: Optional[LwwMap] = None,
        local_aliases: Optional[LwwMap] = None,
    ):
        self.secret_key = secret_key            # immutable once created
        self.name = name or Lww("")
        self.allow_create_bucket = allow_create_bucket or Lww(False, ts=0)
        # bucket_id(bytes32) → BucketKeyPerm
        self.authorized_buckets = authorized_buckets or LwwMap()
        # alias(str) → Optional[bucket_id bytes]
        self.local_aliases = local_aliases or LwwMap()

    def merge(self, other: "KeyParams") -> None:
        self.name.merge(other.name)
        self.allow_create_bucket.merge(other.allow_create_bucket)
        self.authorized_buckets.merge(other.authorized_buckets)
        self.local_aliases.merge(other.local_aliases)

    def pack(self) -> Any:
        return [
            self.secret_key,
            self.name.pack(),
            self.allow_create_bucket.pack(),
            [[k, [e.ts, e.value.pack()]] for k, e in self.authorized_buckets.sorted_items()],
            self.local_aliases.pack(),
        ]

    @classmethod
    def unpack(cls, v: Any) -> "KeyParams":
        auth = LwwMap({
            bytes(k): Lww(BucketKeyPerm.unpack(val), ts=ts) for k, (ts, val) in v[3]
        })
        return cls(
            secret_key=v[0],
            name=Lww.unpack(v[1]),
            allow_create_bucket=Lww.unpack(v[2]),
            authorized_buckets=auth,
            local_aliases=LwwMap.unpack(v[4]),
        )

    def __eq__(self, other: object) -> bool:
        return isinstance(other, KeyParams) and self.pack() == other.pack()


class Key(Entry):
    """P = key_id, S = empty (ref key_table.rs:92-178)."""

    VERSION_MARKER = b"GT01key"

    def __init__(self, key_id: str, state: Optional[Deletable] = None):
        self.key_id = key_id
        self.state: Deletable = state or Deletable.delete()

    @classmethod
    def new(cls, name: str = "unnamed") -> "Key":
        k = cls(generate_key_id(), Deletable.present(KeyParams(generate_secret_key())))
        k.params().name.update(name)
        return k

    @classmethod
    def import_key(cls, key_id: str, secret_key: str, name: str) -> "Key":
        k = cls(key_id, Deletable.present(KeyParams(secret_key)))
        k.params().name.update(name)
        return k

    @property
    def partition_key(self) -> str:
        return self.key_id

    @property
    def sort_key(self) -> str:
        return ""

    def is_tombstone(self) -> bool:
        return self.state.is_deleted()

    def is_deleted(self) -> bool:
        return self.state.is_deleted()

    def params(self) -> Optional[KeyParams]:
        return self.state.get()

    # --- permission checks (ref key_table.rs:128-151) ---

    def allow_read(self, bucket_id: Uuid) -> bool:
        p = self.bucket_permissions(bucket_id)
        return p.allow_read or p.allow_owner

    def allow_write(self, bucket_id: Uuid) -> bool:
        p = self.bucket_permissions(bucket_id)
        return p.allow_write or p.allow_owner

    def allow_owner(self, bucket_id: Uuid) -> bool:
        return self.bucket_permissions(bucket_id).allow_owner

    def bucket_permissions(self, bucket_id: Uuid) -> BucketKeyPerm:
        params = self.params()
        if params is None:
            return BucketKeyPerm.NO_PERMISSIONS
        perm = params.authorized_buckets.get(bytes(bucket_id))
        return perm if perm is not None else BucketKeyPerm.NO_PERMISSIONS

    def merge(self, other: "Key") -> None:
        self.state.merge(other.state)

    def fields(self) -> Any:
        return [
            self.key_id,
            None if self.state.is_deleted() else self.state.value.pack(),
        ]

    @classmethod
    def from_fields(cls, b: Any) -> "Key":
        state = (
            Deletable.delete()
            if b[1] is None
            else Deletable.present(KeyParams.unpack(b[1]))
        )
        return cls(b[0], state)


class KeyTableSchema(TableSchema):
    TABLE_NAME = "key"
    ENTRY = Key

    def matches_filter(self, entry: Key, filter: Any) -> bool:
        from ..table.schema import DeletedFilter

        if filter is None:
            return not entry.is_deleted()
        if isinstance(filter, str) and filter in ("any", "deleted", "not_deleted"):
            return DeletedFilter.matches(filter, entry.is_deleted())
        # pattern filter: match key_id prefix or name substring (ref
        # key_table.rs KeyFilter::MatchesAndNotDeleted)
        if entry.is_deleted():
            return False
        pat = str(filter).lower()
        return entry.key_id.lower().startswith(pat) or (
            pat in entry.params().name.value.lower()
        )
