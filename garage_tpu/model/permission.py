"""Bucket↔key permission flags.

Equivalent of reference src/model/permission.rs:1-64: a timestamped
(allow_read, allow_write, allow_owner) triple merged LWW on the timestamp
with bitwise-or tie-break at equal timestamps.
"""

from __future__ import annotations

from typing import Any, Dict

from ..utils.crdt import Crdt, now_msec


class BucketKeyPerm(Crdt):
    """ref permission.rs BucketKeyPerm."""

    __slots__ = ("timestamp", "allow_read", "allow_write", "allow_owner")

    NO_PERMISSIONS: "BucketKeyPerm"
    ALL_PERMISSIONS: "BucketKeyPerm"

    def __init__(
        self,
        allow_read: bool = False,
        allow_write: bool = False,
        allow_owner: bool = False,
        timestamp: int = None,
    ):
        self.timestamp = now_msec() if timestamp is None else timestamp
        self.allow_read = allow_read
        self.allow_write = allow_write
        self.allow_owner = allow_owner

    def is_any(self) -> bool:
        return self.allow_read or self.allow_write or self.allow_owner

    def merge(self, other: "BucketKeyPerm") -> None:
        # ref permission.rs:37-56: newer timestamp wins outright; equal
        # timestamps or-merge each flag (permissive on true ties)
        if other.timestamp > self.timestamp:
            self.timestamp = other.timestamp
            self.allow_read = other.allow_read
            self.allow_write = other.allow_write
            self.allow_owner = other.allow_owner
        elif other.timestamp == self.timestamp:
            self.allow_read = self.allow_read or other.allow_read
            self.allow_write = self.allow_write or other.allow_write
            self.allow_owner = self.allow_owner or other.allow_owner

    def pack(self) -> Any:
        return [self.timestamp, self.allow_read, self.allow_write, self.allow_owner]

    @classmethod
    def unpack(cls, v: Any) -> "BucketKeyPerm":
        return cls(bool(v[1]), bool(v[2]), bool(v[3]), timestamp=int(v[0]))

    def __eq__(self, other: object) -> bool:
        return isinstance(other, BucketKeyPerm) and self.pack() == other.pack()

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"BucketKeyPerm(r={self.allow_read}, w={self.allow_write}, "
            f"o={self.allow_owner})"
        )


BucketKeyPerm.NO_PERMISSIONS = BucketKeyPerm(timestamp=0)
BucketKeyPerm.ALL_PERMISSIONS = BucketKeyPerm(True, True, True, timestamp=0)
