"""Data model layer — the S3 data model over the table engine.

Equivalent of reference src/model/ (SURVEY.md §2.6): the `Garage` god
object wiring DB + membership + block store + all replicated tables, the
object/version/block_ref metadata chain whose transactional `updated()`
hooks couple S3 metadata to block refcounts, bucket/key/alias CRDT tables,
and distributed index counters.
"""

from .garage import Garage
from .bucket_table import Bucket, BucketParams
from .bucket_alias_table import BucketAlias
from .key_table import Key, KeyParams
from .permission import BucketKeyPerm
from .helper import GarageHelper

__all__ = [
    "Garage",
    "Bucket",
    "BucketParams",
    "BucketAlias",
    "Key",
    "KeyParams",
    "BucketKeyPerm",
    "GarageHelper",
]
