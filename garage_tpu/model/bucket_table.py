"""Bucket table — bucket parameters as CRDTs, fully replicated.

Equivalent of reference src/model/bucket_table.rs (SURVEY.md §2.6):
bucket rows are `Deletable<BucketParams>` where every field is its own
CRDT (authorized keys, alias back-pointers, website/CORS/lifecycle/quota
configs), so concurrent admin operations converge (bucket_table.rs:50-190).
Stored with full-copy replication (every node has all buckets).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..table.schema import Entry, TableSchema
from ..utils.crdt import Crdt, Deletable, Lww, LwwMap, now_msec
from ..utils.data import FixedBytes32, Uuid
from .permission import BucketKeyPerm

EMPTY_SK = ""


class BucketQuotas:
    """ref bucket_table.rs BucketQuotas (max_size/max_objects, both optional)."""

    @staticmethod
    def default() -> Dict[str, Optional[int]]:
        return {"max_size": None, "max_objects": None}


class BucketParams(Crdt):
    """Parameters of an existing bucket (ref bucket_table.rs:68-190)."""

    __slots__ = (
        "creation_date",
        "authorized_keys",
        "aliases",
        "local_aliases",
        "website_config",
        "cors_config",
        "lifecycle_config",
        "quotas",
    )

    def __init__(
        self,
        creation_date: Optional[int] = None,
        authorized_keys: Optional[LwwMap] = None,
        aliases: Optional[LwwMap] = None,
        local_aliases: Optional[LwwMap] = None,
        website_config: Optional[Lww] = None,
        cors_config: Optional[Lww] = None,
        lifecycle_config: Optional[Lww] = None,
        quotas: Optional[Lww] = None,
    ):
        self.creation_date = now_msec() if creation_date is None else creation_date
        # key_id(str) → BucketKeyPerm
        self.authorized_keys = authorized_keys or LwwMap()
        # global alias name(str) → bool (alias points here)
        self.aliases = aliases or LwwMap()
        # (key_id, alias_name) → bool
        self.local_aliases = local_aliases or LwwMap()
        # website: None | {"index_document": str, "error_document": str|None}
        self.website_config = website_config or Lww(None, ts=0)
        # cors: None | [rule dicts]  (see api/s3/cors.py)
        self.cors_config = cors_config or Lww(None, ts=0)
        # lifecycle: None | [rule dicts] (see api/s3/lifecycle.py)
        self.lifecycle_config = lifecycle_config or Lww(None, ts=0)
        self.quotas = quotas or Lww(BucketQuotas.default(), ts=0)

    def merge(self, other: "BucketParams") -> None:
        self.creation_date = min(self.creation_date, other.creation_date)
        self.authorized_keys.merge(other.authorized_keys)
        self.aliases.merge(other.aliases)
        self.local_aliases.merge(other.local_aliases)
        self.website_config.merge(other.website_config)
        self.cors_config.merge(other.cors_config)
        self.lifecycle_config.merge(other.lifecycle_config)
        self.quotas.merge(other.quotas)

    def pack(self) -> Any:
        return [
            self.creation_date,
            [[k, [e.ts, e.value.pack()]] for k, e in self.authorized_keys.sorted_items()],
            self.aliases.pack(),
            [[list(k), e.pack()] for k, e in self.local_aliases.sorted_items()],
            self.website_config.pack(),
            self.cors_config.pack(),
            self.lifecycle_config.pack(),
            self.quotas.pack(),
        ]

    @classmethod
    def unpack(cls, v: Any) -> "BucketParams":
        auth = LwwMap({
            k: Lww(BucketKeyPerm.unpack(val), ts=ts) for k, (ts, val) in v[1]
        })
        local = LwwMap({tuple(k): Lww.unpack(e) for k, e in v[3]})
        return cls(
            creation_date=v[0],
            authorized_keys=auth,
            aliases=LwwMap.unpack(v[2]),
            local_aliases=local,
            website_config=Lww.unpack(v[4]),
            cors_config=Lww.unpack(v[5]),
            lifecycle_config=Lww.unpack(v[6]),
            quotas=Lww.unpack(v[7]),
        )

    def __eq__(self, other: object) -> bool:
        return isinstance(other, BucketParams) and self.pack() == other.pack()


class Bucket(Entry):
    """ref bucket_table.rs:20-66 — P = bucket id (uuid), S = empty."""

    VERSION_MARKER = b"GT01bucket"

    def __init__(self, id: Uuid, state: Optional[Deletable] = None):
        self.id = id
        self.state: Deletable = state or Deletable.present(BucketParams())

    @classmethod
    def new(cls, id: Optional[Uuid] = None) -> "Bucket":
        from ..utils.data import gen_uuid

        return cls(id or gen_uuid())

    @property
    def partition_key(self) -> Uuid:
        return self.id

    @property
    def sort_key(self) -> str:
        return EMPTY_SK

    def is_tombstone(self) -> bool:
        return self.state.is_deleted()

    def is_deleted(self) -> bool:
        return self.state.is_deleted()

    def params(self) -> Optional[BucketParams]:
        return self.state.get()

    def merge(self, other: "Bucket") -> None:
        self.state.merge(other.state)

    def fields(self) -> Any:
        return [bytes(self.id), None if self.state.is_deleted() else self.state.value.pack()]

    @classmethod
    def from_fields(cls, b: Any) -> "Bucket":
        state = (
            Deletable.delete()
            if b[1] is None
            else Deletable.present(BucketParams.unpack(b[1]))
        )
        return cls(Uuid(bytes(b[0])), state)


class BucketTableSchema(TableSchema):
    TABLE_NAME = "bucket_v2"
    ENTRY = Bucket

    def matches_filter(self, entry: Bucket, filter: Any) -> bool:
        from ..table.schema import DeletedFilter

        if filter is None:
            return not entry.is_deleted()
        return DeletedFilter.matches(filter, entry.is_deleted())
