"""Cross-node parity index — the lookup that makes RS survive NODE loss.

The reference's only durability axis is replication
(ref src/rpc/replication_mode.rs:41-56: 3× storage tolerates 2 node
losses).  Local parity sidecars (block/parity.py) already survive
corruption, but a node that dies takes its blocks AND their sidecars
down together.  Distributed parity closes that hole the cheap way:

  - each RS(k, m) parity shard is stored as an ordinary refcounted BLOCK
    (content-hashed, placed by the ring on OTHER nodes, fetched with
    rpc_get_block, scrubbed/resynced like any block — zero new storage
    machinery);
  - this table maps every MEMBER block hash → its codeword: the entry is
    sharded by member hash, so the nodes that would store block h also
    hold the h → codeword record.  A node repairing h reads the entry,
    fetches ≥ k surviving pieces (members + parity blocks) from across
    the cluster, and decodes just the missing row.

Economics vs the reference: replication "none" + RS(8,4) distributed
parity stores 1.5× the data and tolerates the loss of any m = 4 of the
codeword's nodes; the reference's mode "3" stores 3× and tolerates 2.

Entry CRDT: LWW by (timestamp, parity hashes) with an or-merged deleted
flag — a codeword is immutable once encoded (its gid hashes the member
set and geometry), so conflicting writes only ever race identical
content or a newer re-encode of the same member.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ..table.schema import Entry, TableSchema
from ..utils.crdt import CrdtBool
from ..utils.data import Hash


class ParityIndexEntry(Entry):
    VERSION_MARKER = b"GT01parityidx"

    def __init__(self, member: Hash, gid: Hash, timestamp: int,
                 k: int, m: int, member_index: int,
                 members: List[bytes], lengths: List[int],
                 parity_hashes: List[bytes], deleted: bool = False):
        self.member = member
        self.gid = gid
        self.timestamp = timestamp
        self.k = k
        self.m = m
        self.member_index = member_index
        self.members = [bytes(x) for x in members]
        self.lengths = [int(n) for n in lengths]
        self.parity_hashes = [bytes(x) for x in parity_hashes]
        self.deleted = CrdtBool(deleted)

    @property
    def partition_key(self) -> Hash:
        return self.member

    @property
    def sort_key(self) -> bytes:
        return bytes(self.gid)

    def is_tombstone(self) -> bool:
        return self.deleted.value

    def merge(self, other: "ParityIndexEntry") -> None:
        # newer encode of the same (member, gid) wins; content is
        # deterministic from the gid so ties are identical
        if (other.timestamp, other.parity_hashes) > (
                self.timestamp, self.parity_hashes):
            self.timestamp = other.timestamp
            self.k, self.m = other.k, other.m
            self.member_index = other.member_index
            self.members = other.members
            self.lengths = other.lengths
            self.parity_hashes = other.parity_hashes
        self.deleted.merge(other.deleted)

    def fields(self) -> Any:
        return [bytes(self.member), bytes(self.gid), self.timestamp,
                self.k, self.m, self.member_index, self.members,
                self.lengths, self.parity_hashes, self.deleted.value]

    @classmethod
    def from_fields(cls, b: Any) -> "ParityIndexEntry":
        return cls(Hash(bytes(b[0])), Hash(bytes(b[1])), int(b[2]),
                   int(b[3]), int(b[4]), int(b[5]),
                   [bytes(x) for x in b[6]], [int(n) for n in b[7]],
                   [bytes(x) for x in b[8]], bool(b[9]))


PARITY_REF_MARK = b"GTPC"


def parity_ref_version(gid: Hash) -> bytes:
    """The synthetic 'version' uuid under which a codeword's parity
    blocks are BlockRef'd: recognizably marked so version-existence
    repair scans know these refs answer to the parity index, not the
    version table."""
    return PARITY_REF_MARK + bytes(gid)[4:]


def is_parity_ref(version: bytes) -> bool:
    return bytes(version)[:4] == PARITY_REF_MARK


class ParityIndexTableSchema(TableSchema):
    TABLE_NAME = "parity_index"
    ENTRY = ParityIndexEntry

    def __init__(self, block_ref_table=None):
        self.block_ref_table = block_ref_table

    def updated(self, tx, old: Optional[ParityIndexEntry],
                new: Optional[ParityIndexEntry]) -> None:
        """Parity blocks are refcounted through the ordinary BlockRef
        table (block = parity hash, version = marked gid), exactly like
        version rows drive data-block refs (ref version_table.rs
        pattern).  BlockRef partitions by the PARITY hash, so rc lands on
        the nodes whose data ring actually stores the shard — the
        local-rc invariant the block GC/resync/offload machinery assumes.
        (An earlier design increfed from this hook directly, which put rc
        on the INDEX partition's nodes — sharded by MEMBER hash — where
        no shard lives.)  Only the member-0 row drives refs, or each
        parity block would be ref'd k times per codeword."""
        if self.block_ref_table is None:
            return
        from ..utils.data import Uuid
        from .s3.block_ref_table import BlockRef

        ent = old or new
        if ent.member_index != 0:
            return
        was = old is not None and not old.deleted.value
        now = new is not None and not new.deleted.value
        refv = Uuid(parity_ref_version(ent.gid))
        if now and not was:
            for ph in (new.parity_hashes or []):
                self.block_ref_table.data.queue_insert(
                    tx, BlockRef(Hash(ph), refv))
        elif was and not now and new is not None:
            # Decref ONLY on a logical tombstone (new row with
            # deleted=True).  new=None is PHYSICAL removal — partition
            # offload after a layout change (table/sync.py
            # delete_if_equal) or tombstone GC — and says nothing about
            # cluster-wide liveness.  The deleted BlockRefs queued here
            # are sticky or-merged tombstones that propagate everywhere;
            # firing them on offload would decref and GC live parity
            # blocks cluster-wide, permanently stripping erasure
            # coverage (same hazard block_ref_table.py:74-81 guards).
            for ph in (old.parity_hashes or []):
                self.block_ref_table.data.queue_insert(
                    tx, BlockRef(Hash(ph), refv, deleted=True))
