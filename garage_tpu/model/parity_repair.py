"""Cross-node RS decode-repair — rebuild a block no replica can serve.

The last line of the resync fallback chain (local sidecar → replicas →
THIS): look the lost block up in the replicated parity index
(model/parity_index_table.py), fetch ≥ k surviving codeword pieces from
across the cluster — member blocks and parity blocks alike are ordinary
ring-placed blocks — and decode exactly the missing row.  Every fetched
piece is verified by content hash before use and the rebuilt block must
hash to the requested id, so damaged or stale pieces can only cause a
fallback, never wrong data.

The reference has no equivalent: its resync gives up when every replica
is gone (ref src/block/resync.rs:457-468).  Here, with data replication
"none" + RS(8,4) distribution, the cluster stores 1.5× the data and any
block survives the loss of up to m = 4 of its codeword's nodes — versus
the reference's 3× storage tolerating 2.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import numpy as np

from ..utils.data import Hash, block_hash

logger = logging.getLogger("garage_tpu.model.parity_repair")


# How many index rows to consider per member during GC/repair: a block
# can belong to several codewords over its life (re-groupings); tombstones
# keep occupying slots, so the scan must look well past the live ones.
INDEX_SCAN_LIMIT = 64


def make_parity_gc(garage):
    """Bind the GC trigger: fired (post-commit, on the block_ref
    partition's nodes) when a live version-ref for a member block is
    tombstoned.  If NO live version-ref remains, the block is globally
    dead and its parity-index rows tombstone — which, via the member-0
    row, decrefs the codeword's parity blocks so their storage is
    reclaimed by normal block GC.

    The trigger is deliberately NOT physical deletion: a node deleting
    its local copy during migration/offload says nothing about the
    block's global liveness, and GC'ing coverage there would strip
    erasure protection from a block that still exists (with an or-merged
    sticky tombstone, unrecoverably — the gid is deterministic).  The
    block_ref and parity_index tables shard by the same hash, so this
    check reads only local rows."""
    from .parity_index_table import is_parity_ref
    from .s3.block_ref_table import BlockRef

    def on_ref_dropped(h: Hash) -> None:
        task = asyncio.get_running_loop().create_task(_gc_if_dead(garage, h))
        _GC_TASKS.add(task)
        task.add_done_callback(_GC_TASKS.discard)

    async def _gc_if_dead(garage, h: Hash) -> None:
        try:
            from ..table.schema import hash_partition_key

            data = garage.block_ref_table.data
            prefix = bytes(hash_partition_key(bytes(h)))
            for k, raw in data.store.items(prefix, None):
                if k[:32] != prefix:
                    break
                br: BlockRef = data.decode_entry(raw)
                if not br.deleted.value and not is_parity_ref(br.version):
                    return  # still referenced somewhere: keep coverage
            entries = await garage.parity_index_table.get_range(
                bytes(h), None, limit=INDEX_SCAN_LIMIT)
            dead = [e for e in entries if not e.is_tombstone()]
            for e in dead:
                e.deleted.set()
            if dead:
                await garage.parity_index_table.insert_many(dead)
        except Exception:
            logger.debug("parity GC for %s failed (will retry on next "
                         "ref drop)", bytes(h).hex()[:16], exc_info=True)

    return on_ref_dropped


_GC_TASKS: set = set()


def make_parity_reconstructor(garage):
    """Bind a `async h -> plain bytes | None` reconstructor over the
    garage's parity index table + block manager (attached to the block
    manager as `parity_reconstructor`)."""

    async def reconstruct(h: Hash) -> Optional[bytes]:
        try:
            entries = await garage.parity_index_table.get_range(
                bytes(h), None, limit=INDEX_SCAN_LIMIT)
        except Exception:
            logger.warning("parity index unreachable for %s",
                           bytes(h).hex()[:16], exc_info=True)
            return None
        for ent in entries:
            if ent.is_tombstone():
                continue
            data = await _try_codeword(garage, h, ent)
            if data is not None:
                return data
        return None

    return reconstruct


async def _fetch_verified(garage, mh: bytes) -> Optional[bytes]:
    """A codeword piece (member or parity block), verified against its
    content hash.  Tries the ring placement first; if that misses —
    mid-migration after a layout change, the piece may still sit on a
    node the NEW ring no longer lists for it — falls back to asking
    every other alive peer.  O(cluster) worst case, but this only runs
    during disaster repair, where completeness beats elegance."""
    mgr = garage.block_manager
    h = Hash(mh)
    raw = None
    # the repairing node's OWN store first: after a layout change the
    # new ring may route a piece elsewhere while this node still holds
    # the only live copy (observed: repair stalled on pieces sitting in
    # the repairer's own block dir)
    if mgr.is_block_present(h):
        try:
            block = await mgr.read_block(h)
            raw = await asyncio.to_thread(block.decompressed)
        except Exception:
            raw = None
    if raw is not None:
        if bytes(block_hash(raw, mgr.hash_algo)) == bytes(mh):
            return raw
        raw = None
    try:
        raw = await mgr.rpc_get_block(h)
    except Exception as ring_err:
        ring_nodes = {bytes(x) for x in mgr.replication.read_nodes(h)}
        tried = []
        # liveness ORDERS the sweep (likely-up peers first) but never
        # vetoes it: is_up is a stale hint (ping cadence), and skipping a
        # reachable holder during disaster repair turns a recoverable
        # codeword into data loss — a dead peer just fails fast instead
        peers = sorted(
            garage.system.peering.peers.items(),
            key=lambda kv: not kv[1].is_up,
        )
        for nid, st in peers:
            if bytes(nid) in ring_nodes:
                continue
            try:
                resp, stream = await mgr.endpoint.call_streaming(
                    nid, {"t": "get_block", "h": bytes(h)},
                    timeout=30.0,
                )
                if resp.get("err") or stream is None:
                    tried.append(f"{bytes(nid).hex()[:8]}:miss")
                    continue
                from ..block.block import DataBlock, DataBlockHeader

                hdr = DataBlockHeader.unpack(resp["hdr"])
                raw = DataBlock(
                    await stream.read_all(), hdr.compressed).decompressed()
                break
            except Exception as e:
                tried.append(f"{bytes(nid).hex()[:8]}:{type(e).__name__}")
                continue
        if raw is None:
            logger.info(
                "repair fetch of piece %s failed everywhere: ring=%s; "
                "sweep=%s", bytes(mh).hex()[:12], ring_err, tried)
    if raw is None:
        return None
    if bytes(block_hash(raw, mgr.hash_algo)) != bytes(mh):
        logger.warning("repair fetch of piece %s: hash mismatch",
                       bytes(mh).hex()[:12])
        return None
    return raw


async def _try_codeword(garage, h: Hash, ent) -> Optional[bytes]:
    k, m = ent.k, ent.m
    target_i = ent.member_index
    lengths = ent.lengths
    maxlen = max(lengths) if lengths else 0
    if maxlen == 0 or target_i >= len(ent.members):
        return None

    pieces, present = [], []

    def pad(raw: bytes) -> np.ndarray:
        shard = np.zeros(maxlen, dtype=np.uint8)
        shard[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
        return shard

    # surviving data members (fetched concurrently — they live on
    # different nodes, and a dead node costs a full timeout serially)
    others = [i for i in range(len(ent.members)) if i != target_i]
    fetched = await asyncio.gather(
        *[_fetch_verified(garage, ent.members[i]) for i in others])
    for i, raw in zip(others, fetched):
        if raw is None or len(present) >= k:
            continue
        pieces.append(pad(raw))
        present.append(i)
    # implicit zero shards of a partial codeword
    for i in range(len(ent.members), k):
        if len(present) >= k:
            break
        pieces.append(np.zeros(maxlen, dtype=np.uint8))
        present.append(i)
    # parity blocks as needed (verified blobs carry the salt header —
    # strip it to get the shard bytes; see block/parity.py placement)
    if len(present) < k:
        from ..block.parity import unpack_parity_shard

        pfetched = await asyncio.gather(
            *[_fetch_verified(garage, ph) for ph in ent.parity_hashes])
        for j, raw in enumerate(pfetched):
            if raw is None or len(present) >= k:
                continue
            shard = unpack_parity_shard(raw)
            if shard is None:
                continue
            pieces.append(pad(shard))
            present.append(k + j)
    if len(present) < k:
        logger.info(
            "codeword for %s unrecoverable: %d of %d pieces survive",
            bytes(h).hex()[:16], len(present), k)
        return None

    # decode with the ENTRY's geometry (it may predate a codec config
    # change); only the missing row is computed
    from ..ops.codec import CodecParams
    from ..ops.cpu_codec import CpuCodec

    codec = CpuCodec(CodecParams(rs_data=k, rs_parity=m))
    shards = np.stack(pieces)[None, :, :]
    try:
        row = await asyncio.to_thread(
            codec.rs_reconstruct, shards, present, [target_i])
    except Exception:
        logger.exception("distributed decode failed for %s",
                         bytes(h).hex()[:16])
        return None
    out = row[0, 0].tobytes()[: lengths[target_i]]
    if bytes(block_hash(out, garage.block_manager.hash_algo)) != bytes(h):
        logger.warning("distributed decode of %s produced wrong hash",
                       bytes(h).hex()[:16])
        return None
    return out
