"""Cross-node RS decode-repair — rebuild a block no replica can serve.

The last line of the resync fallback chain (local sidecar → replicas →
THIS): look the lost block up in the replicated parity index
(model/parity_index_table.py), fetch ≥ k surviving codeword pieces from
across the cluster — member blocks and parity blocks alike are ordinary
ring-placed blocks — and decode exactly the missing row.  Every fetched
piece is verified by content hash before use and the rebuilt block must
hash to the requested id, so damaged or stale pieces can only cause a
fallback, never wrong data.

The reference has no equivalent: its resync gives up when every replica
is gone (ref src/block/resync.rs:457-468).  Here, with data replication
"none" + RS(8,4) distribution, the cluster stores 1.5× the data and any
block survives the loss of up to m = 4 of its codeword's nodes — versus
the reference's 3× storage tolerating 2.
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import time
from typing import Optional

import numpy as np

from ..utils.background import Worker
from ..utils.data import Hash, block_hash

# True while the current task context is inside a distributed RS decode
# — piece fetches must not recurse into another decode (see
# make_parity_reconstructor)
IN_PARITY_DECODE: contextvars.ContextVar = contextvars.ContextVar(
    "garage_tpu_in_parity_decode", default=False)

logger = logging.getLogger("garage_tpu.model.parity_repair")


# How many index rows to consider per member during GC/repair: a block
# can belong to several codewords over its life (re-groupings); tombstones
# keep occupying slots, so the scan must look well past the live ones.
INDEX_SCAN_LIMIT = 64

# How long after a ring change an empty index quorum read is treated as
# possibly BLIND (new replicas not yet synced) and worth a peer sweep;
# table sync converges well inside this on any healthy cluster.
INDEX_SWEEP_WINDOW_S = 15 * 60.0
# Negative-cache TTL for members whose sweep came back empty — retry
# storms pay one O(peers) sweep per TTL, not one per attempt.
SWEEP_EMPTY_TTL_S = 60.0

# Delay between "looks dead" and the irreversible index tombstone: long
# enough for every node's insert queue to drain a just-queued live ref
# (the worker pushes batches immediately; seconds covers a busy node).
# Tests shrink this.
PARITY_GC_GRACE_S = 5.0


async def has_live_ref(garage, h: Hash) -> bool:
    """Any live non-parity BlockRef for `h`, looking progressively
    further: applied local store → local insert queue → paginated quorum
    read (a dedup'd block can carry any number of refs, and one live ref
    past the page edge must still veto the GC)."""
    from ..table.schema import DeletedFilter, hash_partition_key
    from .parity_index_table import is_parity_ref
    from .s3.block_ref_table import BlockRef  # noqa: F401 — decode type

    data = garage.block_ref_table.data
    prefix = bytes(hash_partition_key(bytes(h)))
    for k, raw in data.store.items(prefix, None):
        if k[:32] != prefix:
            break
        br = data.decode_entry(raw)
        if not br.deleted.value and not is_parity_ref(br.version):
            return True  # still referenced somewhere: keep coverage
    # A live ref from a concurrent PUT may still sit in the local
    # insert queue (queue_insert keys by tree_key = partition prefix +
    # sort key) without having reached the store yet — the index
    # tombstone is sticky, so looking only at the applied store would
    # permanently strip coverage for a block that is very much alive.
    for k, raw in data.insert_queue.items(prefix, None):
        if k[:32] != prefix:
            break
        br = data.decode_entry(raw)
        if not br.deleted.value and not is_parity_ref(br.version):
            return True
    # Local rows can lag the cluster (this node may have missed the
    # PUT's quorum); confirm against a quorum read before tombstoning.
    cursor = None
    while True:
        remote = await garage.block_ref_table.get_range(
            bytes(h), cursor, filter=DeletedFilter.NOT_DELETED,
            limit=INDEX_SCAN_LIMIT)
        for br in remote:
            if not br.deleted.value and not is_parity_ref(br.version):
                return True
        if len(remote) < INDEX_SCAN_LIMIT:
            break
        cursor = bytes(remote[-1].sort_key) + b"\x00"
    return False


async def gc_if_dead(garage, h: Hash, grace: Optional[float] = None,
                     *, pre_checked: bool = False) -> bool:
    """Tombstone `h`'s parity-index rows if no live ref remains anywhere.
    Returns True if rows were tombstoned.  Raises on read/insert failure
    (callers decide whether to retry; keeping coverage is always safe).
    pre_checked: the caller already ran has_live_ref AND served the grace
    (the drain path batches both); only the final re-check runs here."""
    if not pre_checked:
        if await has_live_ref(garage, h):
            return False
        # Grace re-check: a live ref for a deduplicated block may sit in
        # a REMOTE node's insert queue (a version-partition node's hook
        # queued it; its InsertQueueWorker hasn't pushed yet) — invisible
        # to both the local scans and the quorum read.  The queues drain
        # in seconds; waiting out one drain cycle before the irreversible
        # or-merged tombstone closes the practical window.
        await asyncio.sleep(PARITY_GC_GRACE_S if grace is None else grace)
    if await has_live_ref(garage, h):
        return False
    entries = await garage.parity_index_table.get_range(
        bytes(h), None, limit=INDEX_SCAN_LIMIT)
    dead = [e for e in entries if not e.is_tombstone()]
    for e in dead:
        e.deleted.set()
    if dead:
        await garage.parity_index_table.insert_many(dead)
    return bool(dead)


def make_parity_gc(garage):
    """Bind the GC trigger: fired (post-commit, on the block_ref
    partition's nodes) when a live version-ref for a member block is
    tombstoned.  If NO live version-ref remains, the block is globally
    dead and its parity-index rows tombstone — which, via the member-0
    row, decrefs the codeword's parity blocks so their storage is
    reclaimed by normal block GC.

    The trigger is deliberately NOT physical deletion: a node deleting
    its local copy during migration/offload says nothing about the
    block's global liveness, and GC'ing coverage there would strip
    erasure protection from a block that still exists (with an or-merged
    sticky tombstone, unrecoverably).  The block_ref and parity_index
    tables shard by the same hash, so the first-line check reads only
    local rows.

    Dropped hashes accumulate in a pending SET drained by one task —
    a bulk delete tombstoning refs for 100k blocks costs one set of
    hashes and one serialized read loop, not 100k concurrent 5-second
    tasks each firing quorum reads.  The grace sleep is amortized per
    drain batch, not paid per hash.  Best-effort: anything left pending
    at a crash is reclaimed by the ParityGcSweeper's next pass."""

    pending: set = set()
    state = {"drainer": None}

    def on_ref_dropped(h: Hash) -> None:
        pending.add(bytes(h))
        if state["drainer"] is None or state["drainer"].done():
            state["drainer"] = asyncio.get_running_loop().create_task(
                _drain())

    async def _drain() -> None:
        while pending:
            batch = [pending.pop()
                     for _ in range(min(len(pending), GC_DRAIN_BATCH))]
            try:
                looks_dead = []
                for hb in batch:
                    if not await has_live_ref(garage, Hash(hb)):
                        looks_dead.append(hb)
                if looks_dead:
                    # one grace sleep for the whole batch: remote insert
                    # queues drain while we wait, then each candidate is
                    # re-checked by gc_if_dead's first has_live_ref
                    await asyncio.sleep(PARITY_GC_GRACE_S)
                for hb in looks_dead:
                    try:
                        await gc_if_dead(garage, Hash(hb), pre_checked=True)
                    except Exception:
                        logger.debug(
                            "parity GC for %s failed (sweeper will retry)",
                            hb.hex()[:16], exc_info=True)
            except Exception:
                logger.debug("parity GC drain batch failed (sweeper will "
                             "retry)", exc_info=True)

    return on_ref_dropped


GC_DRAIN_BATCH = 256


class ParityGcSweeper(Worker):
    """Convergent backstop for the one-shot ref-drop GC trigger: slowly
    walks this node's LOCAL parity_index rows and re-runs the liveness
    check for each live member row.  Any codeword whose ref-drop event
    was lost — trigger crashed mid-grace, quorum read failed during the
    check, node was down when the delete happened — is reclaimed on a
    later pass, backing the "GC will retry" promise with convergence
    rather than hope."""

    SWEEP_BATCH = 64
    SWEEP_INTERVAL_S = 3600.0  # full-pass cadence
    # inter-batch throttle: every live row costs a (mostly local, but up
    # to quorum) read — "slowly walks" must be enforced, not promised;
    # with 64-row batches this caps the sweep at ~64 rows/s per node
    SWEEP_BATCH_PAUSE_S = 1.0
    # never judge a codeword younger than this: a fresh distribution's
    # FIRST version-ref may still be in flight through remote insert
    # queues, and the sweep's liveness check would see a dead block
    MIN_AGE_MS = 10 * 60 * 1000

    def __init__(self, garage):
        self.garage = garage
        self.cursor: bytes = b""
        self._next_pass = 0.0
        self.swept = 0  # current-pass counters, snapshot at pass end
        self.reclaimed = 0

    def name(self) -> str:
        return "parity GC sweeper"

    async def work(self):
        from ..utils.background import WorkerState
        from ..utils.crdt import now_msec

        if self.cursor == b"" and time.monotonic() < self._next_pass:
            return WorkerState.IDLE
        data = self.garage.parity_index_table.data
        batch = []
        for k, raw in data.store.items(self.cursor, None):
            if k == self.cursor:
                continue
            batch.append((k, raw))
            if len(batch) >= self.SWEEP_BATCH:
                break
        if not batch:
            self.cursor = b""
            self._next_pass = time.monotonic() + self.SWEEP_INTERVAL_S
            self.status().progress = (
                f"last pass: {self.swept} checked, "
                f"{self.reclaimed} reclaimed")
            self.swept = self.reclaimed = 0
            return WorkerState.IDLE
        now = now_msec()
        for k, raw in batch:
            self.cursor = k
            try:
                ent = data.decode_entry(raw)
            except Exception:
                continue
            if (ent.is_tombstone()
                    or now - ent.timestamp < self.MIN_AGE_MS):
                continue
            # Evidence-of-death gate: after a layout change, the
            # block_ref partition for this member may reach this node
            # LATER than the parity_index partition (independent table
            # syncers), and a quorum read interrupted after the two
            # fastest — equally freshly-synced — replicas can also come
            # back empty.  An absent partition is indistinguishable from
            # a dead block by liveness checks alone, so the sweep only
            # judges members whose local block_ref rows exist (a dead
            # block leaves tombstoned refs; a lagging sync leaves
            # nothing).  A fully tombstone-GC'd partition is skipped too
            # — the previous passes had hours to act before that.
            if not self._local_ref_evidence(ent.member):
                continue
            try:
                # EVERY member's row is checked (not only member-0): each
                # member has its own partition's rows, and the lost-event
                # leak applies to each independently.  gc_if_dead(h)
                # tombstones all of member h's rows; the member-0 row's
                # hook is what decrefs the parity blocks.  Full grace
                # applies — the sweep races fresh dedup PUTs exactly like
                # the trigger does, and only sleeps when a row looks dead.
                if await gc_if_dead(self.garage, ent.member):
                    self.reclaimed += 1
            except Exception:
                logger.debug("sweep GC for %s failed (next pass retries)",
                             bytes(ent.member).hex()[:16], exc_info=True)
            self.swept += 1
        await asyncio.sleep(self.SWEEP_BATCH_PAUSE_S)
        return WorkerState.BUSY

    def _local_ref_evidence(self, member: Hash) -> bool:
        """Any block_ref row (live or tombstoned) for the member in the
        LOCAL store — proof the ref partition has actually synced here."""
        from ..table.schema import hash_partition_key

        data = self.garage.block_ref_table.data
        prefix = bytes(hash_partition_key(bytes(member)))
        for k, _raw in data.store.items(prefix, None):
            return k[:32] == prefix
        return False

    async def wait_for_work(self) -> None:
        delay = max(1.0, self._next_pass - time.monotonic())
        await asyncio.sleep(min(delay, 30.0))


def make_parity_reconstructor(garage):
    """Bind a `async h -> plain bytes | None` reconstructor over the
    garage's parity index table + block manager (attached to the block
    manager as `parity_reconstructor`)."""

    async def reconstruct(h: Hash) -> Optional[bytes]:
        # Reentrancy guard: fetching codeword PIECES goes through the
        # same block-read paths that fall back to THIS reconstructor
        # when all replicas fail (block/manager.py streaming read).
        # Without the guard a cluster missing several pieces recurses
        # decode→fetch→decode→… until RecursionError (caught by the
        # chaos soak at ~640 frames).  contextvars propagate into tasks
        # spawned by the decode's gathers, so the ENTIRE fetch subtree
        # of one decode skips further decode attempts; sibling decodes
        # in other request contexts are unaffected.
        if IN_PARITY_DECODE.get():
            return None
        token = IN_PARITY_DECODE.set(True)
        try:
            return await _reconstruct_inner(h)
        finally:
            IN_PARITY_DECODE.reset(token)

    # The index sweep is O(peers) with per-peer timeouts — it must not
    # fire for every genuinely-uncovered block (pre-EC data, parity
    # shards themselves) a resync storm walks.  Two gates: the sweep
    # only runs while a recent ring change makes a blind quorum read
    # PLAUSIBLE (partitions moved, table sync may lag), and a member
    # that just swept empty is negative-cached so retry storms pay one
    # sweep per TTL, not one per attempt.
    sweep_empty: dict = {}

    def _sweep_worthwhile(hb: bytes) -> bool:
        changed = getattr(garage.system, "ring_changed_at", None)
        if (changed is None
                or time.monotonic() - changed > INDEX_SWEEP_WINDOW_S):
            return False
        ts = sweep_empty.get(hb)
        if ts is not None and time.monotonic() - ts < SWEEP_EMPTY_TTL_S:
            return False
        if len(sweep_empty) > 4096:  # bounded: drop the oldest entries
            for k in sorted(sweep_empty, key=sweep_empty.get)[:1024]:
                del sweep_empty[k]
        return True

    async def _reconstruct_inner(h: Hash) -> Optional[bytes]:
        try:
            entries = await garage.parity_index_table.get_range(
                bytes(h), None, limit=INDEX_SCAN_LIMIT)
        except Exception:
            logger.warning("parity index unreachable for %s",
                           bytes(h).hex()[:16], exc_info=True)
            entries = []
        live = [e for e in entries if not e.is_tombstone()]
        # tombstone-only answers are NOT blind — a returned row proves
        # table sync already copied the partition here; only a
        # zero-row (or failed) quorum read can be hiding synced rows
        # on the old replicas
        if not entries and _sweep_worthwhile(bytes(h)):
            # The quorum read is honest but can be BLIND right after a
            # layout change: the member's index partition was reassigned
            # and the NEW replicas answer "no rows" until table sync
            # copies the partition over — while the rows still sit on
            # the old replicas.  A recoverable block would stay
            # unrecovered for a full sync cycle (observed: the degraded
            # bench healed on its 60 s fallback kick, not the decode
            # ladder).  Sweep alive peers for the rows instead — same
            # philosophy as sweep_get_block: on repair paths,
            # completeness beats elegance.
            live = await _sweep_index_entries(garage, h)
            if not live:
                sweep_empty[bytes(h)] = time.monotonic()
        for ent in live:
            data = await try_codeword(garage, h, ent)
            if data is not None:
                return data
        return None

    return reconstruct


async def lookup_index_entries(garage, h: Hash, *, sweep: bool = False
                               ) -> list:
    """Live parity-index rows for member `h` — the quorum read the
    decode ladder and the fleet rebuild scheduler (block/rebuild.py)
    share.  sweep=True falls back to the alive-peer sweep when the read
    returns zero rows (a full-node loss IS a recent ring change, so the
    blind-read window applies)."""
    try:
        entries = await garage.parity_index_table.get_range(
            bytes(h), None, limit=INDEX_SCAN_LIMIT)
    except Exception:
        logger.warning("parity index unreachable for %s",
                       bytes(h).hex()[:16], exc_info=True)
        entries = []
    live = [e for e in entries if not e.is_tombstone()]
    if not entries and sweep:
        live = await _sweep_index_entries(garage, h)
    return live


async def _sweep_index_entries(garage, h: Hash) -> list:
    """Live parity-index rows for member `h` from ANY alive peer: local
    store first (free), then every peer ordered likely-up-first, first
    non-empty answer wins (rows for one member are written together, so
    any holder has the full set; CRDT-merged across duplicates)."""
    from ..table.schema import hash_partition_key

    table = garage.parity_index_table
    ph = hash_partition_key(bytes(h))

    def decode_live(raws) -> dict:
        out: dict = {}
        for v in raws:
            try:
                ent = table.data.decode_entry(bytes(v))
            except Exception:  # noqa: BLE001 — skip undecodable rows
                continue
            key = bytes(ent.sort_key)
            if key in out:
                out[key].merge(ent)
            else:
                out[key] = ent
        return {k: e for k, e in out.items() if not e.is_tombstone()}

    local = decode_live(table.data.read_range(
        Hash(bytes(ph)), None, None, INDEX_SCAN_LIMIT, False))
    if local:
        return list(local.values())
    msg = {"t": "read_range", "ph": bytes(ph), "sk": None, "filter": None,
           "limit": INDEX_SCAN_LIMIT, "rev": False}
    rpc = garage.system.rpc
    peers = sorted(garage.system.peering.peers.items(),
                   key=lambda kv: not kv[1].is_up)
    tried = []
    for nid, _st in peers:
        try:
            resp = await rpc.call(
                table.endpoint, nid, msg, timeout=10.0, idempotent=True)
            rows = decode_live(resp.get("vs", []))
            if rows:
                return list(rows.values())
            tried.append(f"{bytes(nid).hex()[:8]}:empty")
        except Exception as e:  # noqa: BLE001 — next peer
            tried.append(f"{bytes(nid).hex()[:8]}:{type(e).__name__}")
    if tried:
        logger.info("index sweep for %s found nothing: %s",
                    bytes(h).hex()[:12], tried)
    return []


async def _fetch_verified(garage, mh: bytes) -> Optional[bytes]:
    """A codeword piece (member or parity block), verified against its
    content hash — own store → ring placement → every alive peer (the
    migration-aware sweep lives on the block manager, shared with the
    resync fallback chain: block/manager.py sweep_get_block)."""
    return await garage.block_manager.sweep_get_block(Hash(mh))


async def try_codeword(garage, h: Hash, ent) -> Optional[bytes]:
    """Decode member `h` of codeword `ent`: planner (tree/chain/flat
    PPR) first, legacy sweep-everything gather as the completeness
    backstop.  Shared by the resync decode ladder and the rebuild
    scheduler's per-codeword fallback."""
    k, m = ent.k, ent.m
    target_i = ent.member_index
    lengths = ent.lengths
    maxlen = max(lengths) if lengths else 0
    if maxlen == 0 or target_i >= len(ent.members):
        return None

    mgr = garage.block_manager
    # planned, bandwidth-minimal path first (block/repair_plan.py):
    # exact-k fetches ranked by RTT/breaker/zone, partial-sum (PPR)
    # reconstruction when peers support it.  A planner miss falls
    # through to the legacy gather below — its sweep-everything fetch
    # is the completeness backstop (pieces stranded on non-ring nodes
    # after layout churn), so a plan that comes up empty must not cost
    # recoverability the old path had.
    planner = getattr(mgr, "repair_planner", None)
    if planner is not None:
        data = await planner.reconstruct(h, ent)
        if data is not None:
            return data

    pieces, present = [], []

    def pad(raw: bytes) -> np.ndarray:
        shard = np.zeros(maxlen, dtype=np.uint8)
        shard[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
        return shard

    # surviving data members (fetched concurrently — they live on
    # different nodes, and a dead node costs a full timeout serially)
    others = [i for i in range(len(ent.members)) if i != target_i]
    was_local = [mgr.is_block_present(Hash(ent.members[i])) for i in others]
    fetched = await asyncio.gather(
        *[_fetch_verified(garage, ent.members[i]) for i in others])
    for i, raw, loc in zip(others, fetched, was_local):
        if raw is None:
            continue
        if not loc:
            mgr.note_repair_fetch("gather", len(raw))
        if len(present) >= k:
            if not loc:  # only WIRE bytes count as overfetch waste
                mgr.note_repair_overfetch(len(raw))
            continue
        pieces.append(pad(raw))
        present.append(i)
    # implicit zero shards of a partial codeword
    for i in range(len(ent.members), k):
        if len(present) >= k:
            break
        pieces.append(np.zeros(maxlen, dtype=np.uint8))
        present.append(i)
    # parity blocks LAZILY, exactly the gap left by dead members — the
    # old gather fetched all m unconditionally, moving (and discarding)
    # up to (m-1) extra shards per degraded read.  Anything fetched
    # beyond k still lands in repair_overfetch_bytes_total so residual
    # waste is measured, not assumed away.  (`repair_gather_everything`
    # restores the fetch-everything behavior — the bench's baseline
    # emulation knob, never set in production.)
    if len(present) < k:
        from ..block.parity import unpack_parity_shard

        pqueue = list(enumerate(ent.parity_hashes))
        everything = bool(getattr(mgr, "repair_gather_everything", False))
        while len(present) < k and pqueue:
            need = len(pqueue) if everything else k - len(present)
            batch, pqueue = pqueue[:need], pqueue[need:]
            plocal = [mgr.is_block_present(Hash(ph)) for _j, ph in batch]
            pfetched = await asyncio.gather(
                *[_fetch_verified(garage, ph) for _j, ph in batch])
            for (j, _ph), raw, loc in zip(batch, pfetched, plocal):
                if raw is None:
                    continue
                if not loc:
                    mgr.note_repair_fetch("gather", len(raw))
                if len(present) >= k:
                    if not loc:  # only WIRE bytes count as overfetch
                        mgr.note_repair_overfetch(len(raw))
                    continue
                shard = unpack_parity_shard(raw)
                if shard is None:
                    if not loc:
                        mgr.note_repair_overfetch(len(raw))
                    continue
                pieces.append(pad(shard))
                present.append(k + j)
    if len(present) < k:
        logger.info(
            "codeword for %s unrecoverable: %d of %d pieces survive",
            bytes(h).hex()[:16], len(present), k)
        return None

    # decode with the ENTRY's geometry (it may predate a codec config
    # change); only the missing row is computed.  When the entry's
    # geometry matches the live codec, the decode rides the manager's
    # codec feeder — a repair storm's concurrent decodes share one
    # cached RS schedule and one ragged dispatch (ops/feeder.py); a
    # geometry mismatch or absent feeder decodes through a throwaway
    # CPU codec as before.
    shards = np.stack(pieces)[None, :, :]
    feeder = getattr(mgr, "feeder", None)
    live = feeder.codec.params if feeder is not None else None
    try:
        if (feeder is not None and live.rs_data == k
                and live.rs_parity == m):
            row = await feeder.decode_async(shards, present, [target_i])
        else:
            from ..ops.codec import CodecParams
            from ..ops.cpu_codec import CpuCodec

            codec = CpuCodec(CodecParams(rs_data=k, rs_parity=m))
            row = await asyncio.to_thread(
                codec.rs_reconstruct, shards, present, [target_i])
    except Exception:
        logger.exception("distributed decode failed for %s",
                         bytes(h).hex()[:16])
        return None
    out = row[0, 0].tobytes()[: lengths[target_i]]
    if bytes(block_hash(out, garage.block_manager.hash_algo)) != bytes(h):
        logger.warning("distributed decode of %s produced wrong hash",
                       bytes(h).hex()[:16])
        return None
    mgr.note_repair_done(len(out))
    return out
