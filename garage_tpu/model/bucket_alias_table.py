"""Global bucket aliases: name → bucket id.

Equivalent of reference src/model/bucket_alias_table.rs: an LWW pointer
from a DNS-compatible bucket name to a bucket uuid (None = alias deleted),
fully replicated.
"""

from __future__ import annotations

import re
from typing import Any, Optional

from ..table.schema import Entry, TableSchema
from ..utils.crdt import Lww
from ..utils.data import Uuid


def is_valid_bucket_name(n: str) -> bool:
    """AWS S3 bucket naming rules subset (ref bucket_alias_table.rs:60-77)."""
    return (
        3 <= len(n) <= 63
        and re.fullmatch(r"[a-z0-9][a-z0-9\-\.]*[a-z0-9]", n) is not None
        and not re.fullmatch(r"\d+\.\d+\.\d+\.\d+", n)
    )


class BucketAlias(Entry):
    """P = alias name, S = empty; state = Lww[Optional[bucket uuid]]."""

    VERSION_MARKER = b"GT01bktalias"

    def __init__(self, name: str, state: Optional[Lww] = None):
        self._name = name
        self.state: Lww = state if state is not None else Lww(None, ts=0)

    @classmethod
    def new(cls, name: str, bucket_id: Uuid, ts: Optional[int] = None) -> "BucketAlias":
        if not is_valid_bucket_name(name):
            raise ValueError(f"invalid bucket name {name!r}")
        return cls(name, Lww(bytes(bucket_id), ts=ts))

    @property
    def name(self) -> str:
        return self._name

    @property
    def partition_key(self) -> str:
        return self._name

    @property
    def sort_key(self) -> str:
        return ""

    def is_tombstone(self) -> bool:
        return self.state.value is None

    def bucket_id(self) -> Optional[Uuid]:
        v = self.state.value
        return Uuid(v) if v is not None else None

    def merge(self, other: "BucketAlias") -> None:
        self.state.merge(other.state)

    def fields(self) -> Any:
        return [self._name, self.state.pack()]

    @classmethod
    def from_fields(cls, b: Any) -> "BucketAlias":
        return cls(b[0], Lww.unpack(b[1]))


class BucketAliasTableSchema(TableSchema):
    TABLE_NAME = "bucket_alias"
    ENTRY = BucketAlias

    def matches_filter(self, entry: BucketAlias, filter: Any) -> bool:
        return entry.state.value is not None
