"""Model helpers: bucket/key resolution and admin-side mutations.

Equivalent of reference src/model/helper/bucket.rs + key.rs (SURVEY.md
§2.6): bucket name→id resolution through the alias chains, existence and
permission checks, and the alias/permission update operations used by the
admin API and CLI (bucket.rs:40-546).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..utils.data import Uuid
from ..utils.error import GarageError
from .bucket_alias_table import BucketAlias, is_valid_bucket_name
from .bucket_table import Bucket
from .key_table import Key
from .permission import BucketKeyPerm


class NoSuchBucket(GarageError):
    status = 404


class NoSuchKey(GarageError):
    status = 404


class BucketAlreadyExists(GarageError):
    status = 409


class BucketNotEmpty(GarageError):
    status = 409


class GarageHelper:
    def __init__(self, garage):
        self.garage = garage

    # --- resolution (ref helper/bucket.rs:40-120) ---

    async def resolve_global_bucket_name(self, name: str) -> Optional[Uuid]:
        """Name → bucket id: a 64-hex name is interpreted as a raw id,
        otherwise the global alias table decides (bucket.rs:72-98)."""
        if len(name) == 64:
            try:
                return Uuid(bytes.fromhex(name))
            except ValueError:
                pass
        alias = await self.garage.bucket_alias_table.get(name, "")
        if alias is not None and alias.bucket_id() is not None:
            return alias.bucket_id()
        return None

    async def resolve_bucket(self, name: str, api_key: Optional[Key] = None) -> Uuid:
        """Global alias, then the key's local aliases (ref bucket.rs:100-140)."""
        if api_key is not None and api_key.params() is not None:
            local = api_key.params().local_aliases.get(name)
            if local is not None:
                return Uuid(local)
        bid = await self.resolve_global_bucket_name(name)
        if bid is None:
            raise NoSuchBucket(f"bucket {name!r} not found")
        return bid

    async def get_existing_bucket(self, bucket_id: Uuid) -> Bucket:
        b = await self.garage.bucket_table.get(bucket_id, "")
        if b is None or b.is_deleted():
            raise NoSuchBucket(f"bucket {bytes(bucket_id).hex()} not found")
        return b

    async def get_existing_key(self, key_id: str) -> Key:
        k = await self.garage.key_table.get(key_id, "")
        if k is None or k.is_deleted():
            raise NoSuchKey(f"key {key_id} not found")
        return k

    # --- admin mutations (ref helper/bucket.rs:150-546) ---

    async def create_bucket(self, name: str) -> Bucket:
        if not is_valid_bucket_name(name):
            raise GarageError(f"invalid bucket name {name!r}")
        existing = await self.resolve_global_bucket_name(name)
        if existing is not None:
            raise BucketAlreadyExists(f"bucket {name!r} already exists")
        bucket = Bucket.new()
        bucket.params().aliases.update(name, True)
        await self.garage.bucket_table.insert(bucket)
        await self.garage.bucket_alias_table.insert(
            BucketAlias.new(name, bucket.id)
        )
        return bucket

    async def delete_bucket(self, bucket_id: Uuid) -> None:
        """Delete an empty bucket: drop aliases + key grants + the row
        (ref admin/bucket.rs delete_bucket — refuses non-empty buckets)."""
        bucket = await self.get_existing_bucket(bucket_id)
        counts = await self.garage.object_counter.get_totals(bytes(bucket_id))
        mpu_counts = await self.garage.mpu_counter.get_totals(bytes(bucket_id))
        if (
            counts.get("objects", 0) > 0
            or counts.get("unfinished_uploads", 0) > 0
            or mpu_counts.get("uploads", 0) > 0
        ):
            raise BucketNotEmpty(
                f"bucket {bytes(bucket_id).hex()[:16]} is not empty: {counts}"
            )
        params = bucket.params()
        # drop global aliases
        for name, lww in list(params.aliases.items.items()):
            if lww.value:
                alias = await self.garage.bucket_alias_table.get(name, "")
                if alias is not None:
                    alias.state.update(None)
                    await self.garage.bucket_alias_table.insert(alias)
        # revoke key grants + local aliases
        for key_id in list(params.authorized_keys.items.keys()):
            try:
                key = await self.get_existing_key(key_id)
            except NoSuchKey:
                continue
            kp = key.params()
            kp.authorized_buckets.update(bytes(bucket_id), BucketKeyPerm())
            for alias, lww in list(kp.local_aliases.items.items()):
                if lww.value == bytes(bucket_id):
                    kp.local_aliases.update(alias, None)
            await self.garage.key_table.insert(key)
        from ..utils.crdt import Deletable

        bucket.state = Deletable.delete()
        await self.garage.bucket_table.insert(bucket)

    async def set_bucket_key_permissions(
        self, bucket_id: Uuid, key_id: str, perm: BucketKeyPerm
    ) -> None:
        """Grant/revoke, updating both sides of the bidirectional map
        (ref bucket.rs:280-340)."""
        bucket = await self.get_existing_bucket(bucket_id)
        key = await self.get_existing_key(key_id)
        bucket.params().authorized_keys.update(key_id, perm)
        key.params().authorized_buckets.update(bytes(bucket_id), perm)
        await self.garage.bucket_table.insert(bucket)
        await self.garage.key_table.insert(key)

    async def create_key(self, name: str = "unnamed") -> Key:
        key = Key.new(name)
        await self.garage.key_table.insert(key)
        return key

    async def delete_key(self, key: Key) -> None:
        """Revoke from all buckets then tombstone (ref helper/key.rs).
        Also clears the bucket-side (key_id, alias) local-alias mirrors:
        a stale mirror inflates bucket_name_count and lets the last-alias
        guard approve removing a bucket's last USABLE name."""
        params = key.params()
        if params is not None:
            for bid in list(params.authorized_buckets.items.keys()):
                bucket = await self.garage.bucket_table.get(Uuid(bid), "")
                if bucket is not None and not bucket.is_deleted():
                    bucket.params().authorized_keys.update(
                        key.key_id, BucketKeyPerm()
                    )
                    await self.garage.bucket_table.insert(bucket)
            for alias, lww in list(params.local_aliases.items.items()):
                if not lww.value:
                    continue
                bucket = await self.garage.bucket_table.get(
                    Uuid(lww.value), "")
                if bucket is not None and not bucket.is_deleted():
                    bucket.params().local_aliases.update(
                        (key.key_id, alias), False)
                    await self.garage.bucket_table.insert(bucket)
        from ..utils.crdt import Deletable

        key.state = Deletable.delete()
        await self.garage.key_table.insert(key)

    @staticmethod
    def bucket_name_count(bucket: Bucket) -> int:
        """How many live names (global + key-local aliases) the bucket
        has — the single source for every last-alias guard (HTTP admin
        and RPC admin must enforce the same invariant)."""
        p = bucket.params()
        return sum(
            1 for _n, l in p.aliases.items.items() if l.value
        ) + sum(
            1 for _k, l in p.local_aliases.items.items() if l.value
        )

    async def list_buckets(self, limit: int = 1000) -> List[Bucket]:
        """All non-deleted buckets (full-copy table → local range reads,
        iterating every partition)."""
        out = []
        seen = set()
        # full-copy replication: all rows are local; iterate the local tree
        data = self.garage.bucket_table.data
        for _k, v in data.store.items(b"", None):
            try:
                b = data.decode_entry(v)
            except Exception:
                continue
            if not b.is_deleted() and bytes(b.id) not in seen:
                seen.add(bytes(b.id))
                out.append(b)
                if len(out) >= limit:
                    break
        return out

    async def list_keys(self, limit: int = 1000) -> List[Key]:
        out = []
        data = self.garage.key_table.data
        for _k, v in data.store.items(b"", None):
            try:
                k = data.decode_entry(v)
            except Exception:
                continue
            if not k.is_deleted():
                out.append(k)
                if len(out) >= limit:
                    break
        return out
