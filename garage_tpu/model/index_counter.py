"""Distributed sharded index counters.

Equivalent of reference src/model/index_counter.rs (SURVEY.md §2.6):
per-bucket statistics (objects / bytes / unfinished uploads, MPU parts…)
are maintained as a transactional local counter tree on each node plus a
replicated `CounterTable` whose rows hold one (timestamp, value) pair per
node, merged max-timestamp per node (index_counter.rs:86-136).  The total
is the sum over nodes.  Propagation to the counter table rides the table
engine's insert queue (the reference uses a dedicated propagator worker,
index_counter.rs:252+ — same semantics, batched async push).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

from ..table.schema import Entry, TableSchema, tree_key
from ..utils.crdt import now_msec
from ..utils.migrate import pack, unpack

logger = logging.getLogger("garage_tpu.model.counter")


class CounterEntry(Entry):
    """P = counted partition (e.g. bucket uuid bytes), S = "".
    values: name → {node_id(bytes) → [ts, value]}."""

    VERSION_MARKER = b"GT01counter"

    def __init__(self, pk: bytes, sk: str, values: Optional[Dict[str, Dict[bytes, List[int]]]] = None):
        self.pk = bytes(pk)
        self.sk = sk
        self.values = values or {}

    @property
    def partition_key(self) -> bytes:
        return self.pk

    @property
    def sort_key(self) -> str:
        return self.sk

    def merge(self, other: "CounterEntry") -> None:
        for name, nodes in other.values.items():
            mine = self.values.setdefault(name, {})
            for node, tv in nodes.items():
                cur = mine.get(node)
                if cur is None or tv[0] > cur[0]:
                    mine[node] = list(tv)

    def totals(self, node_filter: Optional[List[bytes]] = None) -> Dict[str, int]:
        """Max per-node value (every replica counts the same rows — ref
        index_counter.rs:86-111 filtered_values takes max over layout
        nodes, not sum)."""
        out: Dict[str, int] = {}
        for name, nodes in self.values.items():
            vals = [
                v
                for n, (_ts, v) in nodes.items()
                if node_filter is None or n in node_filter
            ]
            if vals:
                out[name] = max(vals)
        return out

    def is_tombstone(self) -> bool:
        return all(
            v == 0 for nodes in self.values.values() for (_ts, v) in nodes.values()
        )

    def fields(self) -> Any:
        return [
            self.pk,
            self.sk,
            [
                [name, sorted([[n, tv[0], tv[1]] for n, tv in nodes.items()])]
                for name, nodes in sorted(self.values.items())
            ],
        ]

    @classmethod
    def from_fields(cls, b: Any) -> "CounterEntry":
        return cls(
            bytes(b[0]),
            b[1],
            {
                name: {bytes(n): [ts, v] for n, ts, v in nodes}
                for name, nodes in b[2]
            },
        )


def counter_table_schema(name: str):
    """Schema factory: one counter table per counted table (ref
    index_counter.rs COUNTER_TABLE_NAME)."""

    class _CounterSchema(TableSchema):
        TABLE_NAME = name
        ENTRY = CounterEntry

        def matches_filter(self, entry, filter):
            return True

    return _CounterSchema()


class IndexCounter:
    """Local accumulation + async propagation (ref index_counter.rs:165-250)."""

    def __init__(self, system, counter_table, db):
        self.system = system
        self.table = counter_table
        name = counter_table.schema.TABLE_NAME
        self.local_counter = db.open_tree(f"{name}:local")

    def count(
        self,
        tx,
        pk: bytes,
        sk: str,
        old_counts: List[Tuple[str, int]],
        new_counts: List[Tuple[str, int]],
    ) -> None:
        """Apply count deltas inside the counted table's update transaction
        (ref index_counter.rs:202-250)."""
        old_d = dict(old_counts)
        new_d = dict(new_counts)
        deltas = {
            n: new_d.get(n, 0) - old_d.get(n, 0)
            for n in set(old_d) | set(new_d)
            if new_d.get(n, 0) - old_d.get(n, 0) != 0
        }
        if not deltas:
            return
        tk = tree_key(pk, sk)
        cur = tx.get(self.local_counter, tk)
        local = _decode_local(cur)
        ts = now_msec()
        for name, delta in deltas.items():
            ent = local.get(name)
            if ent is None:
                local[name] = [ts, delta]
            else:
                local[name] = [max(ts, ent[0] + 1), ent[1] + delta]
        # the value carries (pk, sk) so offline recount can rebuild the
        # CounterEntry from the row alone (ref index_counter.rs
        # LocalCounterEntry { pk, sk, values })
        tx.insert(self.local_counter, tk, pack([pk, sk, local]))
        # propagate this node's totals through the insert queue
        node = bytes(self.system.id)
        ce = CounterEntry(
            pk, sk, {name: {node: list(tv)} for name, tv in local.items()}
        )
        self.table.data.queue_insert(tx, ce)

    async def get_totals(self, pk: bytes, sk: str = "") -> Dict[str, int]:
        ent = await self.table.get(pk, sk)
        if ent is None:
            return {}
        # filter to nodes still in the layout so departed nodes' stale
        # maxima don't inflate counts forever (ref index_counter.rs:86-90)
        current = [bytes(n) for n in self.system.layout.all_nodes()]
        return ent.totals(node_filter=current or None)

    def local_totals(self, pk: bytes, sk: str = "") -> Dict[str, int]:
        cur = self.local_counter.get(tree_key(pk, sk))
        if cur is None:
            return {}
        return {name: tv[1] for name, tv in _decode_local(cur).items()}

    # --- offline repair (ref index_counter.rs:252-377) ---

    def offline_recount_all(self, counted_table, counter_key) -> Tuple[int, int]:
        """Rebuild every local counter from the counted table's local rows.

        Two passes, mirroring the reference: (1) zero every existing local
        counter with a bumped timestamp (so the zero wins the per-node max-
        timestamp merge everywhere), (2) walk the counted table's store and
        re-accumulate each entry's counts.  Both passes queue propagation
        of this node's totals; the insert-queue worker pushes them when the
        daemon next runs.  MUST run offline — concurrent table updates
        between the passes would be double- or un-counted.

        `counter_key(entry) -> (pk, sk)` maps a counted entry to its
        counter row (bucket id for objects/MPUs; (bucket, partition) for
        K2V).  Returns (n_zeroed, n_recounted_entries).
        """
        db = self.local_counter.db
        node = bytes(self.system.id)
        now = now_msec()
        n_zeroed = 0

        # pass 1: zero old counters
        cursor = b""
        while True:
            batch = []
            k = cursor
            while len(batch) < RECOUNT_BATCH:
                nxt = self.local_counter.get_gt(k)
                if nxt is None:
                    break
                batch.append(nxt)
                k = nxt[0]
            if not batch:
                break
            cursor = batch[-1][0]

            def zero_batch(tx, batch=batch):
                for tk, v in batch:
                    pk, sk, local = _decode_local_full(v)
                    for name, tv in local.items():
                        local[name] = [max(tv[0] + 1, now), 0]
                    tx.insert(self.local_counter, tk, pack([pk, sk, local]))
                    if pk is not None:
                        ce = CounterEntry(pk, sk, {
                            name: {node: list(tv)}
                            for name, tv in local.items()
                        })
                        self.table.data.queue_insert(tx, ce)

            db.transaction(zero_batch)
            n_zeroed += len(batch)

        # pass 2: recount from the counted table's rows
        n_entries = 0
        store = counted_table.data.store
        cursor = b""
        while True:
            batch = []
            k = cursor
            while len(batch) < RECOUNT_BATCH:
                nxt = store.get_gt(k)
                if nxt is None:
                    break
                batch.append(nxt)
                k = nxt[0]
            if not batch:
                break
            cursor = batch[-1][0]
            # aggregate within the batch to one write per counter row
            agg: Dict[bytes, Tuple[bytes, str, Dict[str, int]]] = {}
            for _k, raw in batch:
                ent = counted_table.data.decode_entry(raw)
                pk, sk = counter_key(ent)
                tk = tree_key(pk, sk)
                slot = agg.setdefault(tk, (bytes(pk), sk, {}))
                for name, v in ent.counts():
                    slot[2][name] = slot[2].get(name, 0) + v
                n_entries += 1

            def add_batch(tx, agg=agg):
                ts = now_msec()
                for tk, (pk, sk, counts) in agg.items():
                    cur = tx.get(self.local_counter, tk)
                    local = _decode_local(cur)
                    for name, v in counts.items():
                        ent = local.get(name)
                        if ent is None:
                            local[name] = [max(ts, now + 1), v]
                        else:
                            local[name] = [max(ts, ent[0] + 1), ent[1] + v]
                    tx.insert(self.local_counter, tk, pack([pk, sk, local]))
                    ce = CounterEntry(pk, sk, {
                        name: {node: list(tv)} for name, tv in local.items()
                    })
                    self.table.data.queue_insert(tx, ce)

            db.transaction(add_batch)

        logger.info(
            "counter recount (%s): zeroed %d rows, recounted %d entries",
            self.table.schema.TABLE_NAME, n_zeroed, n_entries,
        )
        return n_zeroed, n_entries


RECOUNT_BATCH = 1000  # ref index_counter.rs recount batches


def _decode_local(cur: Optional[bytes]) -> Dict[str, List[int]]:
    """Value → {name: [ts, v]}, accepting the legacy bare-dict format."""
    if cur is None:
        return {}
    v = unpack(cur)
    if isinstance(v, dict):
        return v  # legacy rows without (pk, sk)
    return v[2]


def _decode_local_full(cur: bytes):
    """Value → (pk | None, sk, {name: [ts, v]})."""
    v = unpack(cur)
    if isinstance(v, dict):
        return None, "", v
    return bytes(v[0]), v[1], v[2]
